"""Continuous-batching scheduler over the paged KV pool.

The TPU-shaped constraint this scheduler exists for: XLA compiles one
executable per input *shape*, so the decode batch must be assembled into a
small closed set of **shape buckets** — (batch rows, pages per sequence)
padded up to the nearest bucket — and never into whatever ragged
composition the traffic happens to produce. With B batch buckets and P
page buckets the engine compiles at most B*P decode executables for the
lifetime of the process (gated by tests/test_serving_compile_gate.py);
everything dynamic (which request sits in which row, how long it is, which
pool pages it owns) travels as *data* through block tables and length
vectors.

Policies (the serving study arxiv 2605.25645 and RPA arxiv 2604.15464
shapes, vLLM idiom):
- admission: FIFO queue; a request is admitted when the pool can hold its
  current tokens and utilization stays under the high watermark (the
  watermark guard is waived when nothing is running, so a big request
  cannot deadlock an empty engine). At most ``max_prefills_per_step``
  admissions per engine step so prefill never starves running decodes.
- deadline load shedding: a *waiting* request whose deadline has passed is
  shed at schedule time (it would miss SLO anyway — do not burn pool pages
  on it). Running requests are never shed.
- preemption-with-requeue: when a running sequence cannot grow into its
  next page, victims are preempted latest-arrival-first (freeing whole
  sequences, not single pages), their generated tokens are kept, and they
  re-enter the *front* of the queue in recompute mode: on re-admission the
  engine prefills prompt+generated and decoding resumes — greedy outputs
  are therefore identical with and without preemption.
"""
from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field

from .kv_cache import PagedKVPool, PoolExhausted


class SequenceStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"   # transiently, while re-queued
    FINISHED = "finished"
    SHED = "shed"
    CANCELLED = "cancelled"
    ABORTED = "aborted"


def bucket_for(n: int, buckets) -> int:
    """Smallest bucket >= n (buckets need not be sorted)."""
    best = None
    for b in buckets:
        if b >= n and (best is None or b < best):
            best = b
    if best is None:
        raise ValueError(f"{n} exceeds the largest bucket in {buckets}")
    return best


@dataclass
class Sequence:
    """Scheduler-side state of one in-flight request."""
    seq_id: str
    prompt_ids: list
    max_new_tokens: int
    arrival: float
    deadline: float | None = None
    temperature: float = 0.0
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)      # generated so far
    status: SequenceStatus = SequenceStatus.WAITING
    num_preemptions: int = 0

    @property
    def total_len(self) -> int:
        """Tokens committed to the KV cache (prompt + generated)."""
        return len(self.prompt_ids) + len(self.tokens)

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.tokens)


@dataclass
class DecodePlan:
    """One fixed-shape decode launch: ``seqs`` padded to ``batch_bucket``
    rows, block tables padded to ``pages_bucket`` columns."""
    seqs: list
    batch_bucket: int
    pages_bucket: int


class SchedulerConfig:
    def __init__(self, *, batch_buckets=(1, 2, 4, 8), pages_buckets=None,
                 max_prefills_per_step=4, now_fn=time.monotonic):
        self.batch_buckets = tuple(sorted(set(batch_buckets)))
        self.pages_buckets = (tuple(sorted(set(pages_buckets)))
                              if pages_buckets is not None else None)
        self.max_prefills_per_step = max_prefills_per_step
        self.now_fn = now_fn

    @staticmethod
    def default_pages_buckets(max_pages_per_seq: int):
        """Powers of two up to (and always including) the per-seq max.
        The engine's default prefill buckets are this ladder scaled by
        page_size — one bucket policy, two units."""
        out, b = [], 1
        while b < max_pages_per_seq:
            out.append(b)
            b *= 2
        out.append(max_pages_per_seq)
        return tuple(sorted(set(out)))


class Scheduler:
    def __init__(self, pool: PagedKVPool, config: SchedulerConfig,
                 max_pages_per_seq: int, metrics=None):
        self.pool = pool
        self.config = config
        self.max_pages_per_seq = max_pages_per_seq
        self.pages_buckets = (config.pages_buckets or
                              SchedulerConfig.default_pages_buckets(
                                  max_pages_per_seq))
        if max(self.pages_buckets) > max_pages_per_seq:
            raise ValueError("pages bucket exceeds max pages per sequence")
        self.metrics = metrics
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        #: sequences preempted during the LAST prepare_decode round; the
        #: engine drains this to surface fresh preemptions exactly once
        self.last_preempted: list[Sequence] = []
        #: watermark hysteresis: once admission halts above the HIGH
        #: watermark, it stays halted until utilization falls below LOW —
        #: prevents admit/preempt thrash right at the high line
        self._admission_paused = False

    # ---- introspection ----
    @property
    def max_num_seqs(self) -> int:
        return max(self.config.batch_buckets)

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    def queue_depth(self) -> int:
        return len(self.waiting)

    # ---- admission ----
    def add(self, seq: Sequence):
        total_pages = self.pool.pages_for(
            len(seq.prompt_ids) + seq.max_new_tokens)
        limit = min(self.pool.capacity, self.max_pages_per_seq,
                    max(self.pages_buckets))
        if total_pages > limit:
            raise ValueError(
                f"request {seq.seq_id}: prompt+max_new_tokens needs "
                f"{total_pages} pages, engine limit is {limit}")
        seq.status = SequenceStatus.WAITING
        self.waiting.append(seq)

    def remove(self, seq_id: str):
        """Drop a sequence wherever it sits (cancellation). Frees pages if
        it was running. Returns the Sequence or None."""
        for s in self.waiting:
            if s.seq_id == seq_id:
                self.waiting.remove(s)
                return s
        for s in self.running:
            if s.seq_id == seq_id:
                self.running.remove(s)
                self.pool.free(seq_id)
                return s
        return None

    def shed_expired(self, now=None) -> list[Sequence]:
        """Deadline-based load shedding over the admission queue.

        The deadline is a waiting-before-START SLO: a request that has
        already produced tokens (i.e. was admitted, then preempted back
        into the queue) is never shed — shedding it would break the
        preemption token-identity guarantee for work already under way.
        """
        now = self.config.now_fn() if now is None else now
        shed, keep = [], deque()
        for s in self.waiting:
            if s.deadline is not None and now > s.deadline \
                    and not s.tokens:
                s.status = SequenceStatus.SHED
                shed.append(s)
            else:
                keep.append(s)
        self.waiting = keep
        if shed and self.metrics is not None:
            self.metrics.shed_requests.inc(len(shed))
        return shed

    def admit(self) -> list[Sequence]:
        """Move FIFO-queue heads into the running set; allocates their KV
        pages. The engine must prefill each returned sequence this step."""
        admitted = []
        if self._admission_paused and self.pool.below_low_watermark():
            self._admission_paused = False
        while self.waiting:
            # admitted seqs are already in self.running — count them once
            if len(self.running) >= self.max_num_seqs:
                break
            if len(admitted) >= self.config.max_prefills_per_step:
                break
            seq = self.waiting[0]
            n_pages = self.pool.pages_for(seq.total_len)
            if n_pages > self.pool.free_pages:
                break
            # watermark admission control: above the high watermark stop
            # taking new work (leave headroom for running seqs to grow),
            # and stay stopped until utilization recovers below the low
            # watermark (hysteresis) — unless the engine is idle, where
            # waiting would deadlock
            busy = bool(self.running) or bool(admitted)
            if busy:
                if self.pool.above_high_watermark(extra_pages=n_pages):
                    self._admission_paused = True
                if self._admission_paused:
                    break
            self.waiting.popleft()
            self.pool.allocate(seq.seq_id, seq.total_len)
            seq.status = SequenceStatus.RUNNING
            self.running.append(seq)
            admitted.append(seq)
        return admitted

    # ---- decode assembly ----
    def preempt(self, seq: Sequence):
        """Free the sequence's pages and requeue it (recompute mode) at the
        FRONT of the queue; generated tokens are preserved."""
        self.running.remove(seq)
        self.pool.free(seq.seq_id)
        seq.status = SequenceStatus.WAITING
        seq.num_preemptions += 1
        self.waiting.appendleft(seq)
        self.last_preempted.append(seq)
        if self.metrics is not None:
            self.metrics.preemptions.inc()

    def finish(self, seq: Sequence, status=SequenceStatus.FINISHED):
        seq.status = status
        if seq in self.running:
            self.running.remove(seq)
        if seq.seq_id in self.pool:
            self.pool.free(seq.seq_id)

    def prepare_decode(self) -> DecodePlan | None:
        """Grow each running sequence's table to cover its next token,
        preempting latest arrivals when the pool runs dry, then assemble
        the fixed-shape decode plan."""
        self.last_preempted = []
        for seq in list(self.running):
            if seq not in self.running:      # preempted below this round
                continue
            while True:
                try:
                    # the last generated token is not cached yet: decode
                    # writes it at slot total_len-1, so pages must cover
                    # total_len tokens after this step
                    self.pool.extend(seq.seq_id, seq.total_len)
                    break
                except PoolExhausted:
                    victim = self._pick_victim(exclude=seq)
                    if victim is None:
                        # nothing else to evict: preempt THIS sequence.
                        # add() guaranteed prompt+max_new fits the empty
                        # pool, so its re-admission always converges.
                        self.preempt(seq)
                        break
                    self.preempt(victim)
        if not self.running:
            return None
        bb = bucket_for(len(self.running), self.config.batch_buckets)
        max_pages = max(self.pool.pages_for(s.total_len)
                        for s in self.running)
        pb = bucket_for(max_pages, self.pages_buckets)
        return DecodePlan(list(self.running), bb, pb)

    def _pick_victim(self, exclude: Sequence) -> Sequence | None:
        candidates = [s for s in self.running if s is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.arrival)


__all__ = ["Scheduler", "SchedulerConfig", "Sequence", "SequenceStatus",
           "DecodePlan", "bucket_for"]
