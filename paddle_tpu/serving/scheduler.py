"""Continuous-batching scheduler over the paged KV pool — ragged edition.

The old TPU-shaped constraint (XLA compiles one executable per input
*shape*) used to force decode batches into a closed set of
(batch, pages) shape buckets plus a separate bucketed prefill ladder —
up to B*P + #prefill_buckets executables. The ragged kernel
(kernels/paged_attention.py) removes the constraint at the source: every
engine step is ONE launch of ONE fixed shape — ``max_num_seqs`` row
slots over a ``step_token_budget``-token query buffer — and everything
request-specific (which row, how many query tokens, which pool pages)
travels as *data* through block tables and (q_start, q_len, kv_len)
metadata. The engine therefore compiles exactly one step executable for
the lifetime of the process (tests/test_serving_compile_gate.py).

A step row is a (sequence, q_len) pair and there is NO prefill/decode
distinction: each sequence has ``cached_len`` tokens committed to the KV
pool out of ``total_len`` known tokens (prompt + generated), and a row
processes the next ``q_len = min(remaining, chunk_size, budget share)``
of them. A fully-caught-up sequence has exactly one uncached token (its
last sampled one) — its row is a decode step, q_len = 1, by the same
formula. A freshly admitted prompt is processed in ``chunk_size``-token
chunks across consecutive steps, INTERLEAVED with every running decode
row in the same launch — long prompts no longer head-of-line-block
decodes behind ``max_prefills_per_step`` whole-prompt prefills; the
budget reserves ``q_block`` tokens per running row first, so decode
progress per step is guaranteed by construction.

Policies (serving study arxiv 2605.25645, RPA arxiv 2604.15464, vLLM):
- admission: FIFO queue; a request is admitted when the pool can hold its
  FIRST chunk and utilization stays under the high watermark (waived when
  nothing is running, so a big request cannot deadlock an empty engine).
  At most ``max_prefills_per_step`` admissions per engine step. An
  optional ``prefix_hook`` (the engine's prefix cache) may fork the
  request onto a live sequence's matching prompt-prefix pages, skipping
  both the re-prefill and the page storage for the shared region.
- deadline load shedding: a *waiting* request whose deadline has passed is
  shed at schedule time. Running requests are never shed.
- preemption-with-requeue: when a sequence cannot grow into its next
  page, victims are preempted latest-arrival-first, their generated
  tokens kept, and they re-enter the *front* of the queue in recompute
  mode (``cached_len`` reset to 0): on re-admission the engine re-chunks
  prompt+generated and decoding resumes — the ragged step computes each
  token's K/V identically regardless of chunk boundaries, so greedy
  outputs are identical with and without preemption.
"""
from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field

from .kv_cache import PagedKVPool, PoolExhausted


class SequenceStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"   # transiently, while re-queued
    FINISHED = "finished"
    SHED = "shed"
    CANCELLED = "cancelled"
    ABORTED = "aborted"


def bucket_for(n: int, buckets) -> int:
    """Smallest bucket >= n (buckets need not be sorted). Kept for the
    legacy bucketed callers/tests; the ragged step itself has one shape
    and never buckets."""
    best = None
    for b in buckets:
        if b >= n and (best is None or b < best):
            best = b
    if best is None:
        raise ValueError(f"{n} exceeds the largest bucket in {buckets}")
    return best


@dataclass
class Sequence:
    """Scheduler-side state of one in-flight request."""
    seq_id: str
    prompt_ids: list
    max_new_tokens: int
    arrival: float
    deadline: float | None = None
    #: absolute e2e SLO: a request still unfinished past this instant is
    #: ABORTED at the next step boundary (mid-flight SLO abort — decoding
    #: tokens nobody will read is shed load, not service), unlike
    #: ``deadline`` which only sheds requests still WAITING to start
    abort_deadline: float | None = None
    temperature: float = 0.0
    #: per-request sampling knobs (None/0 = off, engine passes them as
    #: per-row data into the one jitted step — knobs are data, not shape)
    top_k: int | None = None
    top_p: float | None = None
    #: resolved per-request PRNG seed: every random draw this request
    #: consumes is fold_in(base, seed, generation position, tag), so its
    #: sampled tokens are bit-identical across batch compositions
    seed: int = 0
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)      # generated so far
    status: SequenceStatus = SequenceStatus.WAITING
    num_preemptions: int = 0
    #: tokens whose K/V is committed to the pool (prefix-cache fork sets
    #: it to the shared length at admission; preemption resets it to 0)
    cached_len: int = 0
    #: when the sequence last entered the waiting queue (scheduler
    #: now_fn time base): set at add(), refreshed at preempt() — queue
    #: age = now - enqueued_at feeds the starvation gauges
    enqueued_at: float | None = None
    #: when the FIRST generated token was committed (engine now_fn time
    #: base) — the TTFT numerator; never reset by preemption (the
    #: client saw the token when it streamed, recompute is invisible)
    first_token_at: float | None = None
    #: multi-tenant serving (paddle_tpu.tenancy): the owning tenant
    #: (None = untenanted traffic), the LoRA adapter the request wears
    #: (0 = base model) and its resolved registry slot — the slot rides
    #: the ragged step as per-token DATA, never shape
    tenant_id: str | None = None
    adapter_id: object = 0
    adapter_slot: int = 0
    #: structured shed cause (e.g. "quota_exceeded") — the engine's
    #: finalize turns it into the output's finish_reason
    shed_reason: str | None = None

    @property
    def total_len(self) -> int:
        """Tokens the engine knows (prompt + generated)."""
        return len(self.prompt_ids) + len(self.tokens)

    @property
    def remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def uncached_len(self) -> int:
        """Known tokens not yet in the pool — 1 for a caught-up decode
        row, more while the prompt is still being chunked in."""
        return self.total_len - self.cached_len

    @property
    def all_ids(self) -> list:
        return self.prompt_ids + self.tokens


@dataclass
class BurstPlan:
    """One on-device generation burst: every row is a caught-up decode
    row (uncached_len == 1), and the pool already covers ``cap`` more
    tokens per row — the jitted ``lax.while_loop`` runs up to
    ``burst_len`` sample->append->gate iterations with NO host round
    trip, the host re-syncing scheduler state only at the boundary."""
    rows: list                 # [(Sequence, cap)] cap = max tokens this burst
    burst_len: int             # max(cap) — the loop's trip bound
    cow_copies: int = 0        # copy-on-write page dups pre-claimed


@dataclass
class StepPlan:
    """One fixed-shape ragged launch: ``rows`` are (seq, q_start, q_len)
    with slot starts aligned to ``q_block``, packed into a
    ``token_budget``-token query buffer over ``num_slots`` row slots."""
    rows: list                 # [(Sequence, q_start, q_len)]
    num_slots: int             # fixed row-slot count (max_num_seqs)
    token_budget: int          # fixed packed-query length
    cow_copies: int = 0        # copy-on-write page dups this step
    #: speculative rounds only (prepare_spec): per-row draft candidate
    #: count, aligned with ``rows`` (q_len = spec_len + 1); None on
    #: ordinary decode/prefill rounds
    spec_lens: list | None = None

    @property
    def actual_q_tokens(self) -> int:
        return sum(q_len for _, _, q_len in self.rows)

    @property
    def pad_fraction(self) -> float:
        return 1.0 - self.actual_q_tokens / self.token_budget


class SchedulerConfig:
    def __init__(self, *, max_num_seqs=None, chunk_size=32, q_block=8,
                 step_token_budget=None, max_prefills_per_step=4,
                 now_fn=time.monotonic, batch_buckets=None,
                 pages_buckets=None):
        # legacy bucket knobs: max(batch_buckets) used to bound the decode
        # batch — it still sets the row-slot count when max_num_seqs is
        # not given; pages_buckets is obsolete (one launch shape) and
        # accepted only so older callers keep working
        if max_num_seqs is None:
            max_num_seqs = max(batch_buckets) if batch_buckets else 8
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if q_block < 1:
            raise ValueError("q_block must be >= 1")
        self.max_num_seqs = int(max_num_seqs)
        self.q_block = int(q_block)
        self.chunk_size = int(chunk_size)
        if step_token_budget is None:
            step_token_budget = self.max_num_seqs * self.q_block + \
                -(-self.chunk_size // self.q_block) * self.q_block
        if step_token_budget % self.q_block != 0:
            raise ValueError(
                f"step_token_budget {step_token_budget} not a multiple of "
                f"q_block {self.q_block}")
        if step_token_budget < self.max_num_seqs * self.q_block:
            raise ValueError(
                "step_token_budget must reserve q_block tokens per row "
                f"({self.max_num_seqs} rows x q_block {self.q_block})")
        self.step_token_budget = int(step_token_budget)
        self.max_prefills_per_step = max_prefills_per_step
        self.now_fn = now_fn


class Scheduler:
    def __init__(self, pool: PagedKVPool, config: SchedulerConfig,
                 max_pages_per_seq: int, metrics=None):
        self.pool = pool
        self.config = config
        self.max_pages_per_seq = max_pages_per_seq
        self.metrics = metrics
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        #: sequences preempted during the LAST prepare_step round; the
        #: engine drains this to surface fresh preemptions exactly once
        self.last_preempted: list[Sequence] = []
        #: watermark hysteresis: once admission halts above the HIGH
        #: watermark, it stays halted until utilization falls below LOW —
        #: prevents admit/preempt thrash right at the high line
        self._admission_paused = False
        #: q_len granted to each running seq by the current planning round
        self._granted: dict[str, int] = {}
        #: cluster drain hook (serving/cluster.py): True freezes
        #: admission entirely — running rows finish, waiting rows sit
        #: (or are withdrawn by the cluster for requeue elsewhere)
        self.admission_blocked = False
        #: multi-tenant economy (paddle_tpu.tenancy.TenantPolicy): when
        #: set, admission switches to stride-scheduled weighted-fair
        #: pick over per-tenant queues with token-bucket quota gating;
        #: None (the default) keeps the bare-FIFO path byte-identical
        self.policy = None

    # ---- introspection ----
    @property
    def max_num_seqs(self) -> int:
        return self.config.max_num_seqs

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    def queue_depth(self) -> int:
        return len(self.waiting)

    def queue_ages(self, now=None) -> list[float]:
        """Seconds each waiting request has sat in the queue since it was
        last (re-)enqueued — the starvation signal behind the
        ``queue_age_p99_s`` / ``max_queue_wait_s`` gauges. Preemption
        refreshes a sequence's enqueue timestamp: its age measures THIS
        wait, not lifetime."""
        now = self.config.now_fn() if now is None else now
        return [now - (s.enqueued_at if s.enqueued_at is not None
                       else s.arrival)
                for s in self.waiting]

    def max_queue_wait(self, now=None) -> float:
        ages = self.queue_ages(now)
        return max(ages) if ages else 0.0

    # ---- admission ----
    def add(self, seq: Sequence):
        total_pages = self.pool.pages_for(
            len(seq.prompt_ids) + seq.max_new_tokens)
        limit = min(self.pool.capacity, self.max_pages_per_seq)
        if total_pages > limit:
            raise ValueError(
                f"request {seq.seq_id}: prompt+max_new_tokens needs "
                f"{total_pages} pages, engine limit is {limit}")
        seq.status = SequenceStatus.WAITING
        seq.enqueued_at = self.config.now_fn()
        self.waiting.append(seq)

    def remove(self, seq_id: str):
        """Drop a sequence wherever it sits (cancellation). Frees pages if
        it was running. Returns the Sequence or None."""
        for s in self.waiting:
            if s.seq_id == seq_id:
                self.waiting.remove(s)
                return s
        for s in self.running:
            if s.seq_id == seq_id:
                self.running.remove(s)
                self.pool.free(seq_id)
                return s
        return None

    def shed_expired(self, now=None) -> list[Sequence]:
        """Deadline-based load shedding over the admission queue.

        The deadline is a waiting-before-START SLO: a request that has
        already produced tokens (i.e. was admitted, then preempted back
        into the queue) is never shed — shedding it would break the
        preemption token-identity guarantee for work already under way.
        """
        now = self.config.now_fn() if now is None else now
        shed, keep = [], deque()
        for s in self.waiting:
            if s.deadline is not None and now > s.deadline \
                    and not s.tokens:
                s.status = SequenceStatus.SHED
                shed.append(s)
            else:
                keep.append(s)
        self.waiting = keep
        if shed and self.metrics is not None:
            self.metrics.shed_requests.inc(len(shed))
        return shed

    def abort_expired(self, now=None) -> list[Sequence]:
        """Mid-flight SLO abort: collect every sequence — RUNNING rows
        included — whose absolute e2e ``abort_deadline`` has passed.
        Shedding only at admission keeps burning steps on requests whose
        client has already timed out; this catches them at the step
        boundary instead. The caller finalizes each one (a structured
        ``RequestOutput`` with reason ``deadline_exceeded``; pages are
        freed through the normal ``finish`` path, so CoW refcounts and
        pinned chains stay intact). This method only COLLECTS — state
        changes stay in one place (``finish``)."""
        now = self.config.now_fn() if now is None else now
        return [s for s in list(self.running) + list(self.waiting)
                if s.abort_deadline is not None and now > s.abort_deadline]

    def admit(self, prefix_hook=None) -> list[Sequence]:
        """Move FIFO-queue heads into the running set. Claims the pages
        of each admission's FIRST chunk (later chunks claim lazily inside
        ``prepare_step``); ``prefix_hook(seq)``, when given, may fork the
        sequence onto cached prompt-prefix pages first and returns the
        shared token count (0 on miss).

        With a :class:`~paddle_tpu.tenancy.TenantPolicy` attached
        (``self.policy``) admission instead stride-picks the next
        fundable tenant's oldest request (weighted-fair + token-bucket
        quotas); without one, this FIFO body runs unchanged."""
        if self.policy is not None:
            return self._admit_weighted(prefix_hook)
        admitted = []
        if self.admission_blocked:
            return admitted
        if self._admission_paused and self.pool.below_low_watermark():
            self._admission_paused = False
        while self.waiting:
            # admitted seqs are already in self.running — count them once
            if len(self.running) >= self.max_num_seqs:
                break
            if len(admitted) >= self.config.max_prefills_per_step:
                break
            seq = self.waiting[0]
            # a PARKED sequence (two-tier pools, serving/kv_tier.py)
            # still owns its table: re-admission must restore its
            # spilled pages — that restore IS its first-chunk cost
            parked = seq.seq_id in self.pool
            if parked:
                # restore cost + the first chunk's growth past the
                # pages the sequence already owns, priced against
                # headroom that EXCLUDES the sequence's own cold pages
                # (spilling the row being restored frees no net HBM)
                first_target = min(seq.cached_len + self.config.chunk_size,
                                   seq.total_len)
                n_pages = self.pool.spilled_page_count(seq.seq_id) \
                    + max(0, self.pool.pages_for(first_target)
                          - len(self.pool.block_table(seq.seq_id)))
                avail = self.pool.restore_headroom(seq.seq_id)
            else:
                first_len = min(self.config.chunk_size, seq.total_len)
                n_pages = self.pool.pages_for(first_len)
                # available = free + reclaimable pinned-exclusive pages
                # (a pool full of evictable prefix cache must still
                # admit)
                avail = self.pool.available_pages
            if n_pages > avail:
                break
            # watermark admission control: above the high watermark stop
            # taking new work (leave headroom for running seqs to grow),
            # and stay stopped until utilization recovers below the low
            # watermark (hysteresis) — unless the engine is idle, where
            # waiting would deadlock
            busy = bool(self.running) or bool(admitted)
            if busy:
                if self.pool.above_high_watermark(extra_pages=n_pages):
                    self._admission_paused = True
                if self._admission_paused:
                    break
            self.waiting.popleft()
            if parked:
                # exact-byte resume: prefetch-hit or counted stall, the
                # restored KV is identical — cached_len survives
                # parking. Restore AND the first chunk's growth can
                # both fall short if headroom moved under us: defer,
                # don't die — the row keeps its queue-front slot and
                # retries next round (a restore that landed stays
                # landed; the retry's restore is then a no-op).
                shared = seq.cached_len
                first_target = min(shared + self.config.chunk_size,
                                   seq.total_len)
                try:
                    self.pool.restore_sequence(seq.seq_id)
                    self.pool.extend(seq.seq_id, first_target)
                except PoolExhausted:
                    self.waiting.appendleft(seq)
                    break
            else:
                shared = 0
                if prefix_hook is not None:
                    shared = int(prefix_hook(seq) or 0)
                if not shared:
                    self.pool.allocate(seq.seq_id, 0)
                seq.cached_len = shared
                # reserve the first chunk's pages now (the watermark
                # math above priced them in) but commit nothing yet —
                # prepare_step owns the committed length
                first_target = min(shared + self.config.chunk_size,
                                   seq.total_len)
                self.pool.extend(seq.seq_id, first_target)
            self.pool.set_seq_len(seq.seq_id, shared)
            seq.status = SequenceStatus.RUNNING
            self.running.append(seq)
            admitted.append(seq)
            if self.metrics is not None:
                self.metrics.prefills.inc()
        return admitted

    def _admit_weighted(self, prefix_hook=None) -> list[Sequence]:
        """Weighted-fair admission (paddle_tpu.tenancy.TenantPolicy):
        each round the policy stride-picks the fundable tenant with the
        lowest virtual pass and admits that tenant's OLDEST waiting
        request — same pool/watermark feasibility gates as the FIFO
        path, but the pick order interleaves tenants by weight and a
        tenant whose token bucket cannot fund its next request simply
        does not compete (its requests sit, or are quota-shed by
        :meth:`shed_quota`)."""
        admitted = []
        if self.admission_blocked:
            return admitted
        if self._admission_paused and self.pool.below_low_watermark():
            self._admission_paused = False
        while self.waiting:
            if len(self.running) >= self.max_num_seqs:
                break
            if len(admitted) >= self.config.max_prefills_per_step:
                break
            now = self.config.now_fn()
            idx = self.policy.pick(self.waiting, now=now)
            if idx is None:
                break                  # no tenant can fund its next ask
            seq = self.waiting[idx]
            parked = seq.seq_id in self.pool
            if parked:
                first_target = min(seq.cached_len + self.config.chunk_size,
                                   seq.total_len)
                n_pages = self.pool.spilled_page_count(seq.seq_id) \
                    + max(0, self.pool.pages_for(first_target)
                          - len(self.pool.block_table(seq.seq_id)))
                avail = self.pool.restore_headroom(seq.seq_id)
            else:
                first_len = min(self.config.chunk_size, seq.total_len)
                n_pages = self.pool.pages_for(first_len)
                avail = self.pool.available_pages
            if n_pages > avail:
                break
            busy = bool(self.running) or bool(admitted)
            if busy:
                if self.pool.above_high_watermark(extra_pages=n_pages):
                    self._admission_paused = True
                if self._admission_paused:
                    break
            del self.waiting[idx]
            if parked:
                shared = seq.cached_len
                first_target = min(shared + self.config.chunk_size,
                                   seq.total_len)
                try:
                    self.pool.restore_sequence(seq.seq_id)
                    self.pool.extend(seq.seq_id, first_target)
                except PoolExhausted:
                    self.waiting.insert(idx, seq)
                    break
            else:
                shared = 0
                if prefix_hook is not None:
                    shared = int(prefix_hook(seq) or 0)
                if not shared:
                    self.pool.allocate(seq.seq_id, 0)
                seq.cached_len = shared
                first_target = min(shared + self.config.chunk_size,
                                   seq.total_len)
                self.pool.extend(seq.seq_id, first_target)
            self.pool.set_seq_len(seq.seq_id, shared)
            seq.status = SequenceStatus.RUNNING
            self.running.append(seq)
            admitted.append(seq)
            self.policy.on_admit(seq, now=now)
            if self.metrics is not None:
                self.metrics.prefills.inc()
        return admitted

    def shed_quota(self, now=None) -> list[Sequence]:
        """Quota-based load shedding (the noisy-neighbor valve): ask
        the policy which waiting requests sit beyond their tenant's
        fundable horizon (current bucket + ``shed_window_s`` of refill)
        and shed them with the structured reason ``"quota_exceeded"``.
        Preempted-back requests (``seq.tokens`` non-empty) are never
        shed — same work-already-under-way rule as
        :meth:`shed_expired`. No-op without a policy."""
        if self.policy is None:
            return []
        now = self.config.now_fn() if now is None else now
        shed = []
        for i in self.policy.shed_candidates(self.waiting, now=now):
            s = self.waiting[i]
            if s.tokens:
                continue
            s.status = SequenceStatus.SHED
            s.shed_reason = "quota_exceeded"
            del self.waiting[i]
            shed.append(s)
        return shed

    def prefetch_candidates(self, limit: int) -> list:
        """Seq ids of the first ``limit`` PARKED sequences in queue
        order — the restores the next admission round will want. The
        engine issues cursor-ahead staging for these at the END of each
        step, so by the time admission claims them the background
        thread has had a full step of compute to overlap."""
        out = []
        for s in self.waiting:
            if len(out) >= limit:
                break
            if s.seq_id in self.pool:
                out.append(s.seq_id)
        return out

    # ---- ragged step assembly ----
    def preempt(self, seq: Sequence):
        """Free the sequence's page mappings and requeue it (recompute
        mode) at the FRONT of the queue; generated tokens are
        preserved, ``cached_len`` resets — re-admission re-chunks
        prompt+generated through the same ragged step."""
        self.running.remove(seq)
        self.pool.free(seq.seq_id)
        seq.cached_len = 0
        seq.status = SequenceStatus.WAITING
        seq.num_preemptions += 1
        seq.enqueued_at = self.config.now_fn()
        self.waiting.appendleft(seq)
        self.last_preempted.append(seq)
        if self.metrics is not None:
            self.metrics.preemptions.inc()

    def park(self, seq: Sequence):
        """Two-tier preemption (serving/kv_tier.py): spill the victim's
        exclusive pages to the host arena and requeue it at the queue
        FRONT with ``cached_len`` INTACT — re-admission restores the
        exact bytes instead of recomputing the prefix. Everything else
        mirrors :meth:`preempt` (same counters, same requeue position),
        so the client-visible lifecycle is identical and greedy tokens
        stay bit-identical either way."""
        self.running.remove(seq)
        self.pool.park(seq.seq_id)
        seq.status = SequenceStatus.WAITING
        seq.num_preemptions += 1
        seq.enqueued_at = self.config.now_fn()
        self.waiting.appendleft(seq)
        self.last_preempted.append(seq)
        if self.metrics is not None:
            self.metrics.preemptions.inc()

    def _relieve_pressure(self, seq: Sequence) -> bool:
        """One pressure-relief move after :class:`PoolExhausted`, in
        cost order: deepen the cold spill of already-parked sequences
        (costs nothing semantically) -> park the victim into the host
        tier (exact-byte restore later) -> classic recompute preemption
        (the arena is full or the victim has nothing spillable).
        Returns True to retry the claim, False when ``seq`` itself was
        evicted (the caller's planning loop drops the row)."""
        pool = self.pool
        if hasattr(pool, "spill_cold") and pool.spill_cold() > 0:
            return True
        victim = self._pick_victim(exclude=seq)
        target = victim if victim is not None else seq
        if hasattr(pool, "can_park") and pool.can_park(target.seq_id):
            self.park(target)
        else:
            self.preempt(target)
        return target is not seq

    def finish(self, seq: Sequence, status=SequenceStatus.FINISHED):
        seq.status = status
        if seq in self.running:
            self.running.remove(seq)
        elif any(s is seq for s in self.waiting):
            # mid-flight aborts can finalize a WAITING sequence (e.g. a
            # preempted-back row whose e2e deadline passed in the queue)
            self.waiting = deque(s for s in self.waiting if s is not seq)
        if seq.seq_id in self.pool:
            self.pool.free(seq.seq_id)

    def prepare_burst(self, burst_tokens: int) -> BurstPlan | None:
        """Plan an on-device generation burst, or None when ineligible.

        Eligible only when EVERY running sequence is a caught-up decode
        row (its whole prompt committed, exactly one uncached token):
        prefill chunks need per-chunk host packing, so any in-flight
        prompt falls back to the per-step ragged path. Claims (and
        CoWs) each row's pages for up to ``min(burst_tokens,
        remaining_new_tokens)`` appends up front — the burst loop never
        crosses into an unowned page — preempting latest arrivals when
        the pool runs dry, exactly like :meth:`prepare_step`. Rows the
        planning itself preempts drop out of the burst (they re-chunk
        through per-step on re-admission)."""
        self.last_preempted = []
        if burst_tokens <= 1 or not self.running:
            return None
        for s in self.running:
            if s.uncached_len != 1 or s.cached_len < len(s.prompt_ids):
                return None
        rows, cow = [], 0
        for seq in list(self.running):
            if seq.status is not SequenceStatus.RUNNING:
                continue                      # preempted by an earlier row
            cap = min(burst_tokens, seq.remaining_new_tokens)
            while True:
                try:
                    cow += self.pool.prepare_append(
                        seq.seq_id, seq.cached_len + cap)
                    break
                except PoolExhausted:
                    # shrink before shooting: a shorter burst that fits
                    # the row's already-owned pages beats preempting a
                    # neighbor into a full re-prefill (the per-step
                    # path's 1-token grant, generalized)
                    fit = len(self.pool.block_table(seq.seq_id)) \
                        * self.pool.page_size - seq.cached_len
                    if 1 <= fit < cap:
                        cap = fit
                        continue
                    if not self._relieve_pressure(seq):
                        break
            if seq.status is SequenceStatus.RUNNING:
                rows.append((seq, cap))
        # a LATER row's PoolExhausted retry can pick an already-planned
        # row as its preemption victim — drop stale rows (their pool
        # entries are freed) instead of handing _launch_burst a
        # sequence with no block table (prepare_step's rebuild-from-
        # running discipline)
        rows = [(s, c) for s, c in rows
                if s.status is SequenceStatus.RUNNING]
        if not rows:
            return None
        return BurstPlan(rows, burst_len=max(cap for _, cap in rows),
                         cow_copies=cow)

    def prepare_spec(self, k: int) -> StepPlan | None:
        """Plan a speculative-verification round, or None when ineligible.

        Eligible only when EVERY running sequence is a caught-up decode
        row (like :meth:`prepare_burst`): each row gets ``q_len =
        spec_len + 1`` query tokens — its one uncached token plus
        ``spec_len = min(k, remaining - 1)`` draft candidates — so the
        whole round is one prefill-shaped launch of the SAME ragged
        executable. Pages are claimed (and CoW'd) for the full ``k+1``
        appends up front; the engine rolls the committed length back to
        what verification actually accepted.

        ``spec_len`` deliberately depends ONLY on the request's own
        state (k and remaining_new_tokens), never on pool pressure or
        co-scheduling — shrinking it under pressure would change which
        PRNG stream positions get drafted vs directly sampled and break
        the bit-reproducibility contract. Pressure is answered the
        per-step way: preempt latest arrivals (recompute replays the
        same streams)."""
        self.last_preempted = []
        if k < 1 or not self.running:
            return None
        for s in self.running:
            if s.uncached_len != 1 or s.cached_len < len(s.prompt_ids):
                return None
        cfg = self.config
        qb = cfg.q_block
        rows, cow = [], 0
        for seq in list(self.running):
            if seq.status is not SequenceStatus.RUNNING:
                continue                  # preempted by an earlier row
            spec = min(k, seq.remaining_new_tokens - 1)
            while True:
                try:
                    cow += self.pool.prepare_append(
                        seq.seq_id, seq.cached_len + spec + 1)
                    break
                except PoolExhausted:
                    if not self._relieve_pressure(seq):
                        break
            if seq.status is SequenceStatus.RUNNING:
                rows.append((seq, spec))
        rows = [(s, c) for s, c in rows
                if s.status is SequenceStatus.RUNNING]
        if not rows:
            return None
        plan_rows, spec_lens, cursor = [], [], 0
        for seq, spec in rows:
            plan_rows.append((seq, cursor, spec + 1))
            spec_lens.append(spec)
            cursor += -(-(spec + 1) // qb) * qb
        assert cursor <= cfg.step_token_budget, \
            "spec round overflows the step token budget (engine init " \
            "must size the budget for max_num_seqs x (k+1))"
        return StepPlan(plan_rows, num_slots=self.max_num_seqs,
                        token_budget=cfg.step_token_budget,
                        cow_copies=cow, spec_lens=spec_lens)

    def prepare_step(self) -> StepPlan | None:
        """Grant each running sequence its step-token share, grow/CoW its
        pages to cover the granted tokens (preempting latest arrivals
        when the pool runs dry), then pack the fixed-shape ragged plan."""
        cfg = self.config
        qb = cfg.q_block
        self.last_preempted = []
        self._granted = {}
        cow = 0
        budget_left = cfg.step_token_budget
        pending = list(self.running)
        for idx, seq in enumerate(pending):
            # preemption flips status to WAITING immediately, so a status
            # check is an O(1) liveness test (no dataclass-__eq__ list
            # membership scans in the per-step hot path)
            if seq.status is not SequenceStatus.RUNNING:
                continue
            # reserve one q_block for every not-yet-granted row behind us
            # so a fat prefill chunk can never starve their decode slots
            behind = sum(qb for s in pending[idx + 1:]
                         if s.status is SequenceStatus.RUNNING)
            allowed = budget_left - behind
            q_len = min(seq.uncached_len, cfg.chunk_size, allowed)
            assert q_len >= 1, "budget must cover q_block per running row"
            while True:
                try:
                    cow += self.pool.prepare_append(
                        seq.seq_id, seq.cached_len + q_len)
                    break
                except PoolExhausted:
                    # spill-cold -> park victim -> recompute-preempt.
                    # False = THIS sequence was evicted (add()
                    # guaranteed prompt+max_new fits the empty pool, so
                    # its re-admission always converges).
                    if not self._relieve_pressure(seq):
                        break
            if seq.status is SequenceStatus.RUNNING:
                self._granted[seq.seq_id] = q_len
                budget_left -= -(-q_len // qb) * qb
        if not self.running:
            return None
        rows, cursor = [], 0
        for seq in self.running:
            q_len = self._granted[seq.seq_id]
            rows.append((seq, cursor, q_len))
            cursor += -(-q_len // qb) * qb
        assert cursor <= cfg.step_token_budget
        return StepPlan(rows, num_slots=self.max_num_seqs,
                        token_budget=cfg.step_token_budget, cow_copies=cow)

    def _pick_victim(self, exclude: Sequence) -> Sequence | None:
        candidates = [s for s in self.running if s is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.arrival)


__all__ = ["BurstPlan", "Scheduler", "SchedulerConfig", "Sequence",
           "SequenceStatus", "StepPlan", "bucket_for"]
