"""Speculative decoding: int4 draft proposals, one-pass ragged verification.

The serving engine's decode cost is one full target-model launch per
generated token. This module cuts that to less than one: a small DRAFT
model (served off the existing ``quantize_params(mode="weight_only_int4")``
low-bit path) proposes ``k`` tokens per scheduled decode row, and the
target model verifies all ``k+1`` positions in ONE ragged step —
verification rows are just prefill-shaped chunks (``q_len = k + 1``) in
the engine's existing fixed-shape executable, so the serving trace-count
gate stays at 1 and a fully-accepted round commits ``k+1`` tokens for a
single target launch.

Acceptance is standard rejection sampling (Leviathan et al. /
speculative sampling): candidate ``d_i`` drawn from the draft
distribution ``q_i`` is accepted with probability
``min(1, p_{i-1}(d_i) / q_i(d_i))`` against the target distribution
``p_{i-1}`` at the same position; the first rejection resamples from the
normalized residual ``max(p - q, 0)`` and a fully-accepted round samples
one bonus token from ``p_k``. The induced output distribution is EXACTLY
the target-only sampling distribution (tests/test_spec_decode.py proves
the identity numerically on a small vocab), and because greedy rows'
"distributions" are one-hot argmaxes (models/generation.sampling_probs),
the rule degenerates to argmax-equality on greedy rows — spec-on greedy
output is token-identical to spec-off and to sequential
``Generator.generate``.

Randomness: every draw is a per-request stream —
``fold_in(fold_in(fold_in(base, request_seed), generation_position),
tag)`` with distinct tags for draft sampling, acceptance uniforms, and
the residual/bonus draw — so a request's sampled tokens are
bit-reproducible regardless of batch composition, chunk boundaries, or
preemption-recompute (models/generation.request_keys).

KV bookkeeping: the target step appends K/V for all ``k+1`` verified
positions before attention (it must — attention reads them); when only
``j <= k`` candidates survive, the engine ROLLS BACK the pool's
committed length (``PagedKVPool.rollback``) without freeing pages — the
rejected tail slots are garbage the next append overwrites, and
attention never reads past the committed length. The draft runs the
same protocol against its own small paged pool (same ``PagedKVPool``
block-table machinery, fp pages).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.flags import define_flag
from ..models.generation import (_logits, _rms_norm, _rope, _wmat,
                                 extract_params, request_keys, sample_rows,
                                 sampling_probs)
from ..kernels.paged_attention import ragged_paged_attention
from .kv_cache import NULL_PAGE, PagedKVPool, PoolExhausted


def _check_spec_tokens(v):
    if int(v) < 0:
        raise ValueError(
            f"FLAGS_spec_decode_tokens must be >= 0, got {v!r}")


define_flag("spec_decode_tokens", int, 0,
            "speculative-decoding draft length k: how many tokens the "
            "draft model proposes per scheduled decode row, verified by "
            "the target in ONE ragged step (q_len = k+1 per row). 0 (the "
            "default) disables speculation; takes effect only on an "
            "LLMEngine constructed with draft_model=...",
            on_set=_check_spec_tokens)

define_flag("fusion_probe_barrier", bool, False,
            "DEBUG/forensics only: insert a jax.lax.optimization_barrier "
            "between the ragged layer's attention epilogue and the o-proj "
            "at trace time, splitting the hot fused region. This is the "
            "fusion-forensics INJECTED REGRESSION (tools/proxy_bench.py "
            "--defuse): fusion/kernel counts rise and the gate must fail. "
            "Never set in production — it exists to prove the gate fires.")

#: stream tags for the per-request PRNG streams (request_keys): the
#: draft's proposal draw, the verifier's acceptance uniform, and the
#: residual/bonus/plain-sampling draw all at one generation position
#: must be independent
DRAFT_TAG, ACCEPT_TAG, FINAL_TAG = 0, 1, 2


def _ragged_packing(q_starts, q_lens, T):
    """Row/liveness masks of a packed query buffer: ``tok_row[t]`` is
    the row slot token ``t`` belongs to, ``live[t]`` whether it sits
    inside that row's ``q_len`` (slot padding and pad rows are dead)."""
    tok_row = (jnp.searchsorted(q_starts, jnp.arange(T, dtype=jnp.int32),
                                side="right") - 1)
    tok_row = jnp.maximum(tok_row, 0)
    live = (jnp.arange(T) - q_starts[tok_row]) < q_lens[tok_row]
    return tok_row, live


def _ragged_fp_layer(lyr, h, Kp, Vp, positions, tbls, tok_row, live,
                     q_starts, q_lens, kv_lens, cfg, page_size, max_pages,
                     q_block, interpret, *, adapters=None, slots=None):
    """One fp decoder layer of the ragged forward: qkv proj -> rope ->
    page scatter append -> ragged attention -> o proj -> mlp. Returns
    ``(h, Kp, Vp)``.

    This is THE fp layer body — the engine's ragged step (fp pools) and
    the draft worker's forward both call it, so draft/target numerics
    cannot drift (a silent divergence here would collapse speculative
    acceptance with nothing pointing at the cause). The engine's int8
    pool branch stays in engine.py: its append/attention contract
    (running-amax requant, scale-aware gather) is different machinery,
    not a copy of this.

    ``adapters``/``slots`` (multi-tenant LoRA, paddle_tpu.tenancy):
    this layer's ``{proj: (A [S, r, d_in], B [S, d_out, r])}`` slab and
    the per-token slot vector ``[T]`` — each projection then adds the
    batched per-request delta (slot 0 is the all-zero base-model slot).
    None (the default) adds NO operands, so adapter-free engines lower
    byte-identical HLO."""
    ps = page_size
    H, Hkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    T = h.shape[1]

    def lo(p):
        if adapters is None:
            return None
        A, B = adapters[p]
        return (A, B, slots)

    x = _rms_norm(h, lyr["ln1"], cfg.rms_norm_eps)
    q = _wmat(x, lyr["q"], lora=lo("q")).reshape(1, T, H, d)
    k = _wmat(x, lyr["k"], lora=lo("k")).reshape(1, T, Hkv, d)
    v = _wmat(x, lyr["v"], lora=lo("v")).reshape(1, T, Hkv, d)
    q = _rope(q, positions[None], cfg.rope_theta, d)
    k = _rope(k, positions[None], cfg.rope_theta, d)
    kt = jnp.transpose(k[0], (1, 0, 2))                  # [Hkv, T, d]
    vt = jnp.transpose(v[0], (1, 0, 2))
    # scatter every live token's K/V into its page slot; dead tokens
    # (slot padding / pad rows) land on the null page, never live data
    page_idx = jnp.clip(positions // ps, 0, max_pages - 1)
    page = jnp.where(live, tbls[tok_row, page_idx], NULL_PAGE)
    slot = page * ps + positions % ps
    npages = Kp.shape[1]
    Kp = Kp.reshape(Hkv, npages * ps, d).at[:, slot].set(kt) \
        .reshape(Hkv, npages, ps, d)
    Vp = Vp.reshape(Hkv, npages * ps, d).at[:, slot].set(vt) \
        .reshape(Hkv, npages, ps, d)
    o = ragged_paged_attention(q[0], Kp, Vp, tbls, q_starts, q_lens,
                               kv_lens, q_block=q_block,
                               interpret=interpret)
    from ..core.flags import GLOBAL_FLAGS
    if GLOBAL_FLAGS.get("fusion_probe_barrier"):
        # trace-time injected regression (FLAGS_fusion_probe_barrier):
        # the barrier forbids fusion across the attention->o-proj seam,
        # splitting the layer's hot fused region — exactly the defect
        # the probe_hlo_fusion proxy gates exist to catch
        (o,) = jax.lax.optimization_barrier((o,))
    h = h + _wmat(o.reshape(1, T, H * d), lyr["o"], lora=lo("o"))
    x = _rms_norm(h, lyr["ln2"], cfg.rms_norm_eps)
    h = h + _wmat(jax.nn.silu(_wmat(x, lyr["gate"], lora=lo("gate")))
                  * _wmat(x, lyr["up"], lora=lo("up")),
                  lyr["down"], lora=lo("down"))
    return h, Kp, Vp


def speculative_sample(target_logits, draft_tokens, draft_probs, spec_lens,
                       temps, top_ks, top_ps, base_key, seeds, sample_pos):
    """The in-graph rejection sampler: target logits at ``k+1`` verify
    positions per row -> committed tokens.

    target_logits [R, K+1, V]; draft_tokens [R, K]; draft_probs
    [R, K, V] (the EXACT per-position distributions the draft sampled
    from); spec_lens [R] in [0, K] (0 = plain row: no candidates, the
    output is one direct sample from ``p_0`` — exactly the non-spec
    engine's sampling path); temps/top_ks/top_ps [R] per-row knobs;
    seeds/sample_pos [R] per-request stream state (sample_pos = the
    generation index of the row's FIRST committed token this round).

    Returns ``(out_tokens [R, K+1], n_out [R])``: ``out_tokens[r, :j]``
    are the accepted draft candidates (``j = n_out - 1``) and
    ``out_tokens[r, j]`` is the residual resample (on rejection) or the
    bonus/plain sample — ``n_out`` tokens commit, in order.
    """
    R, K1, _V = target_logits.shape
    K = K1 - 1
    # per-position target sampling distributions (greedy rows: one-hot)
    p = jax.vmap(lambda lg: sampling_probs(lg, temps, top_ks, top_ps),
                 in_axes=1, out_axes=1)(target_logits)     # [R, K+1, V]
    rows = jnp.arange(R)
    if K > 0:
        p_at = jnp.take_along_axis(p[:, :K], draft_tokens[..., None],
                                   -1)[..., 0]             # [R, K]
        q_at = jnp.take_along_axis(draft_probs, draft_tokens[..., None],
                                   -1)[..., 0]
        ratio = p_at / jnp.maximum(q_at, 1e-30)
        # acceptance uniforms off the SAME stream derivation every
        # sampler in the repo uses (request_keys) — one definition
        u = jax.vmap(
            lambda i: jax.vmap(jax.random.uniform)(
                request_keys(base_key, seeds, sample_pos + i,
                             ACCEPT_TAG)),
            out_axes=1)(jnp.arange(K))                     # [R, K]
        cand = jnp.arange(K)[None, :] < spec_lens[:, None]
        accept = (u < ratio) & cand
        # leading-accept run length: candidates commit strictly in order
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), -1), -1)
    else:
        n_acc = jnp.zeros((R,), jnp.int32)
    rejected = n_acc < spec_lens
    p_fin = p[rows, n_acc]                                 # [R, V]
    if K > 0:
        # first-rejection residual: max(p - q, 0) renormalized — the
        # distribution that makes the committed token EXACTLY target-
        # distributed. A zero residual (p == q) can only coincide with
        # acceptance, so the p_fin fallback is never actually drawn.
        q_fin = draft_probs[rows, jnp.minimum(n_acc, K - 1)]
        res = jnp.maximum(p_fin - q_fin, 0.0)
        rs = jnp.sum(res, -1, keepdims=True)
        res = jnp.where(rs > 0, res / jnp.maximum(rs, 1e-30), p_fin)
        dist = jnp.where(rejected[:, None], res, p_fin)
    else:
        dist = p_fin
    fkeys = request_keys(base_key, seeds, sample_pos + n_acc, FINAL_TAG)
    y = jax.vmap(jax.random.categorical)(fkeys, jnp.log(dist)) \
        .astype(jnp.int32)
    if K > 0:
        padded = jnp.pad(draft_tokens, ((0, 0), (0, 1)))
        out = jnp.where(jnp.arange(K + 1)[None, :] < n_acc[:, None],
                        padded, 0)
        out = out.at[rows, n_acc].set(y)
    else:
        out = y[:, None]
    return out.astype(jnp.int32), (n_acc + 1).astype(jnp.int32)


class DraftWorker:
    """The draft side of speculative decoding: an int4-quantized small
    model with its OWN paged KV pool (same ``PagedKVPool`` block-table
    machinery as the target, fp pages), kept in sync with the engine's
    committed sequences and asked for ``k`` proposals per decode row.

    One jitted fixed-shape ragged forward serves BOTH duties — catch-up
    chunks (committing prompt/accepted tokens the draft has not seen)
    and the k proposal steps (q_len = 1 rows) — so the draft compiles
    one executable, mirroring the engine's trace-count discipline.

    The pool's committed length per sequence IS the draft's sync state:
    ``sync`` drives it to the engine's ``cached_len`` before proposing,
    and ``commit`` rolls it back after verification (rejected
    candidates' K/V become garbage the next append overwrites).
    """

    def __init__(self, model, *, target_cfg, page_size, max_num_seqs,
                 max_pages_per_seq, num_pages, step_token_budget, q_block,
                 chunk_size, seed=0, quantized_mode="weight_only_int4",
                 interpret=None):
        self.cfg = cfg = model.config
        if cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab {cfg.vocab_size} != target vocab "
                f"{target_cfg.vocab_size}: speculative verification "
                f"compares distributions over one vocabulary")
        self.params = extract_params(model)
        self.quantized_mode = quantized_mode
        if quantized_mode is not None:
            from ..quantization.low_bit import quantize_params
            self.params = quantize_params(self.params, quantized_mode)
        self.page_size = page_size
        self.max_num_seqs = max_num_seqs
        self.max_pages_per_seq = max_pages_per_seq
        self.q_block = q_block
        self.chunk_size = chunk_size
        self.step_token_budget = step_token_budget
        self.pool = PagedKVPool(
            cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim,
            num_pages=num_pages, page_size=page_size,
            dtype=self.params["embed"].dtype)
        if interpret is None:
            from ..kernels import _on_tpu
            interpret = not _on_tpu()
        self._interpret = interpret
        self._base_key = jax.random.key(seed)
        self._launched = False
        #: jitted draft launches this worker issued (sync + propose) —
        #: the draft-side dispatch forensics the metrics snapshot exports.
        #: A whole k-step proposal round is ONE launch (the lax.scan
        #: burst); catch-up sync chunks stay one launch per chunk round.
        self.launches = 0
        #: per-k jitted proposal bursts (k is the scan trip count — one
        #: executable per distinct k, and an engine uses one k for life)
        self._propose_jits: dict = {}
        self._propose_launched = False
        self._build_fwd()

    # ------------------------------------------------------------------
    def _build_fwd(self):
        cfg = self.cfg
        ps = self.page_size
        qb = self.q_block
        T = self.step_token_budget
        PPS = self.max_pages_per_seq
        interpret = self._interpret

        def fwd(params, kv, tokens, positions, tbls, q_starts, q_lens,
                kv_lens, sample_idx, base_key, seeds, gpos, temps, top_ks,
                top_ps):
            # one ragged forward (the SHARED fp layer body — the same
            # function the engine's fp ragged step runs): rows are
            # chunks during sync, q_len=1 during the proposal loop —
            # one executable either way
            tok_row, live = _ragged_packing(q_starts, q_lens, T)
            h = params["embed"][tokens][None]                # [1, T, hid]
            new_kv = []
            for lyr, (Kp, Vp) in zip(params["layers"], kv):
                h, Kp, Vp = _ragged_fp_layer(
                    lyr, h, Kp, Vp, positions, tbls, tok_row, live,
                    q_starts, q_lens, kv_lens, cfg, ps, PPS, qb,
                    interpret)
                new_kv.append((Kp, Vp))
            h = _rms_norm(h, params["norm"], cfg.rms_norm_eps)
            logits = _logits(params, h[0, sample_idx], cfg)  # [R, V]
            keys = request_keys(base_key, seeds, gpos, DRAFT_TAG)
            tok = sample_rows(logits, keys, temps, top_ks, top_ps)
            probs = sampling_probs(logits, temps, top_ks, top_ps)
            return tok, probs, new_kv

        from ..kernels import _on_tpu
        donate = (1,) if _on_tpu() else ()
        self._fwd_jit = jax.jit(fwd, donate_argnums=donate)

    def decode_cache_size(self) -> int:
        """Compile count of the draft catch-up forward (expected: 1)."""
        try:
            return int(self._fwd_jit._cache_size())
        except Exception:
            return 1 if self._launched else 0

    def propose_cache_size(self) -> int:
        """Compile count of the k-step proposal burst (expected: 1 —
        one scan executable per engine-lifetime k)."""
        try:
            return sum(int(fn._cache_size())
                       for fn in self._propose_jits.values())
        except Exception:
            return 1 if self._propose_launched else 0

    def _build_propose(self, k):
        """ONE jitted ``lax.scan`` over the k proposal steps (ROADMAP
        item 4's last leftover): the q_len=1 rows, per-step packing,
        sampling and KV appends all live in the loop body, so a whole
        proposal round costs one host dispatch where the host loop paid
        k. The body reuses the same shared fp layer body / packing /
        sampling functions as the per-step path, and reproduces the
        host loop's cursor packing exactly (live rows pack first, one
        q_block each), so the draft's candidates and reported
        distributions match the unrolled launches.
        """
        cfg = self.cfg
        ps = self.page_size
        qb = self.q_block
        T = self.step_token_budget
        PPS = self.max_pages_per_seq
        interpret = self._interpret

        def burst(params, kv, tbls, cur0, base, spec_lens, seeds, gpos0,
                  temps, top_ks, top_ps, base_key):
            def body(carry, j):
                kv, cur = carry
                live = j < spec_lens                           # [R]
                q_lens = live.astype(jnp.int32)
                # the host loop's packing: live rows pack first, one
                # q_block of budget each; dead rows start past T
                starts_raw = (jnp.cumsum(q_lens) - q_lens) * qb
                q_starts = jnp.where(live, starts_raw, T)
                tok_buf = jnp.zeros((T,), jnp.int32) \
                    .at[q_starts].set(cur, mode="drop")
                pos_buf = jnp.zeros((T,), jnp.int32) \
                    .at[q_starts].set(base + j, mode="drop")
                kv_lens = jnp.where(live, base + j + 1, 0)
                tbl = jnp.where(live[:, None], tbls, NULL_PAGE)
                sample_idx = jnp.where(live, starts_raw, 0)
                tok_row, live_tok = _ragged_packing(q_starts, q_lens, T)
                h = params["embed"][tok_buf][None]
                new_kv = []
                for lyr, (Kp, Vp) in zip(params["layers"], kv):
                    h, Kp, Vp = _ragged_fp_layer(
                        lyr, h, Kp, Vp, pos_buf, tbl, tok_row, live_tok,
                        q_starts, q_lens, kv_lens, cfg, ps, PPS, qb,
                        interpret)
                    new_kv.append((Kp, Vp))
                h = _rms_norm(h, params["norm"], cfg.rms_norm_eps)
                logits = _logits(params, h[0, sample_idx], cfg)
                keys = request_keys(base_key, seeds, gpos0 + j, DRAFT_TAG)
                tok = sample_rows(logits, keys, temps, top_ks, top_ps)
                probs = sampling_probs(logits, temps, top_ks, top_ps)
                tok = jnp.where(live, tok, 0)
                return (new_kv, jnp.where(live, tok, cur)), (tok, probs)

            (kv, _), (toks, probs) = jax.lax.scan(
                body, (kv, cur0), jnp.arange(k, dtype=jnp.int32))
            return toks, probs, kv                 # [k, R], [k, R, V]

        from ..kernels import _on_tpu
        donate = (1,) if _on_tpu() else ()
        return jax.jit(burst, donate_argnums=donate)

    # ------------------------------------------------------------------
    # host-side lifecycle
    # ------------------------------------------------------------------
    def drop(self, seq_id):
        """Forget a sequence (finished / preempted / cancelled): frees
        its draft pool pages. Re-admission re-syncs from scratch."""
        if seq_id in self.pool:
            self.pool.free(seq_id)

    def _ensure(self, seq):
        if seq.seq_id not in self.pool:
            self.pool.allocate(seq.seq_id, 0)

    def _dispatch(self, rows, seeds, gpos, temps, top_ks, top_ps):
        """Pack one fixed-shape draft launch. ``rows`` maps row slot ->
        (tokens, start_pos) — q_len 0 rows are pad slots."""
        T, R, PPS = (self.step_token_budget, self.max_num_seqs,
                     self.max_pages_per_seq)
        qb = self.q_block
        tokens = np.zeros((T,), np.int32)
        positions = np.zeros((T,), np.int32)
        tbls = np.full((R, PPS), NULL_PAGE, np.int32)
        q_starts = np.full((R,), T, np.int32)
        q_lens = np.zeros((R,), np.int32)
        kv_lens = np.zeros((R,), np.int32)
        sample_idx = np.zeros((R,), np.int32)
        cursor = 0
        for i, ent in enumerate(rows):
            if ent is None:
                continue
            seq_id, toks, start = ent
            n = len(toks)
            if n == 0:
                continue
            tokens[cursor:cursor + n] = toks
            positions[cursor:cursor + n] = np.arange(start, start + n)
            tbls[i] = self.pool.padded_block_table(seq_id, PPS)
            q_starts[i] = cursor
            q_lens[i] = n
            kv_lens[i] = start + n
            sample_idx[i] = cursor + n - 1
            cursor += -(-n // qb) * qb
        assert cursor <= T, "draft launch overflow"
        self.launches += 1
        self._launched = True
        tok, probs, new_kv = self._fwd_jit(
            self.params, self.pool.kv, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(tbls),
            jnp.asarray(q_starts), jnp.asarray(q_lens),
            jnp.asarray(kv_lens), jnp.asarray(sample_idx), self._base_key,
            jnp.asarray(seeds), jnp.asarray(gpos), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps))
        self.pool.kv = new_kv
        # tokens come to host (the proposal loop feeds them back and the
        # verifier packs them into the query buffer); the [R, V] probs
        # stay a DEVICE array — the verifier consumes them on-device
        return np.asarray(tok), probs

    def sync(self, seqs):
        """Drive every sequence's draft pool length to the engine's
        committed ``cached_len`` (chunked catch-up: fresh prompts, the
        consumed-but-unverified candidate of a fully-accepted round,
        preemption-recompute restarts). Multiple launches if the
        deficits exceed one step's token budget."""
        R = self.max_num_seqs
        zeros = np.zeros((R,), np.int32)
        zf = np.zeros((R,), np.float32)
        ones = np.ones((R,), np.float32)
        for seq in seqs:
            self._ensure(seq)
        while True:
            # deficits re-read from the pool each round: prepare_append
            # commits, so every dispatched launch makes progress
            rows = [None] * R
            budget = self.step_token_budget
            qb = self.q_block
            for i, seq in enumerate(seqs):
                dlen = self.pool.seq_len(seq.seq_id)
                deficit = seq.cached_len - dlen
                if deficit <= 0:
                    continue
                n = min(deficit, self.chunk_size, (budget // qb) * qb)
                if n < 1:
                    continue               # next launch picks it up
                budget -= -(-n // qb) * qb
                try:
                    self.pool.prepare_append(seq.seq_id, dlen + n)
                except PoolExhausted as e:
                    raise PoolExhausted(
                        f"draft pool exhausted syncing {seq.seq_id!r}: "
                        f"{e} — size the draft pool like the target's "
                        f"(LLMEngine draft_num_pages)") from e
                rows[i] = (seq.seq_id, seq.all_ids[dlen:dlen + n], dlen)
            if not any(r is not None for r in rows):
                break
            self._dispatch(rows, zeros, zeros, zf, zeros, ones)

    def propose(self, seqs, spec_lens, k):
        """Run up to ``k`` q_len=1 proposal steps over the synced rows
        in ONE jitted ``lax.scan`` burst (one host dispatch per spec
        round — ``launches`` rises by 1, not k); rows sit out
        iterations past their own ``spec_lens`` entry (no append, no
        claim). Returns ``(draft_tokens [n, k] host, draft_probs
        [R, k, V] DEVICE)`` — ``draft_tokens`` aligns with ``seqs``
        (the verifier packs them into its query buffer), the probs
        never round-trip through the host; slots past a row's spec_len
        hold garbage the rejection sampler provably never reads
        (candidate masking by ``spec_lens``). Sequences must be
        caught-up decode rows already synced to ``cached_len``."""
        n_rows = len(seqs)
        V = self.cfg.vocab_size
        R = self.max_num_seqs
        PPS = self.max_pages_per_seq
        d_toks = np.zeros((n_rows, k), np.int32)
        if k == 0 or not any(spec_lens):
            return d_toks, jnp.zeros((R, k, V), jnp.float32)
        seeds = np.zeros((R,), np.int32)
        gpos = np.zeros((R,), np.int32)
        temps = np.zeros((R,), np.float32)
        top_ks = np.zeros((R,), np.int32)
        top_ps = np.ones((R,), np.float32)
        cur = np.zeros((R,), np.int32)
        base = np.zeros((R,), np.int32)
        spec = np.zeros((R,), np.int32)
        tbls = np.full((R, PPS), NULL_PAGE, np.int32)
        for i, seq in enumerate(seqs):
            if spec_lens[i] > 0:
                self.pool.prepare_append(
                    seq.seq_id, seq.cached_len + spec_lens[i])
                tbls[i] = self.pool.padded_block_table(seq.seq_id, PPS)
            cur[i] = seq.all_ids[-1]
            base[i] = seq.cached_len
            spec[i] = spec_lens[i]
            seeds[i] = seq.seed or 0
            gpos[i] = len(seq.tokens)
            temps[i] = seq.temperature
            top_ks[i] = seq.top_k or 0
            top_ps[i] = 1.0 if seq.top_p is None else seq.top_p
        fn = self._propose_jits.get(k)
        if fn is None:
            fn = self._propose_jits[k] = self._build_propose(k)
        self.launches += 1
        self._launched = True
        self._propose_launched = True
        toks, probs, new_kv = fn(
            self.params, self.pool.kv, jnp.asarray(tbls),
            jnp.asarray(cur), jnp.asarray(base), jnp.asarray(spec),
            jnp.asarray(seeds), jnp.asarray(gpos), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps), self._base_key)
        self.pool.kv = new_kv
        toks = np.asarray(toks)                            # [k, R]
        for i in range(n_rows):
            s = spec_lens[i]
            if s > 0:
                d_toks[i, :s] = toks[:s, i]
        # [R, k, V]; stays a device array — the verifier consumes it
        return d_toks, jnp.transpose(probs, (1, 0, 2))

    def commit(self, seq_id, cached_old, accepted, spec_len):
        """Roll the draft pool back to the verified state: of the
        ``spec_len`` tokens the proposal loop appended (the row's last
        committed token + its first ``spec_len - 1`` candidates), the
        first ``min(accepted + 1, spec_len)`` survive — a fully-accepted
        round's last candidate was never consumed by the draft, so the
        next ``sync`` chunks it in."""
        if seq_id not in self.pool:
            return
        self.pool.rollback(seq_id,
                           cached_old + min(accepted + 1, spec_len))


__all__ = ["DraftWorker", "speculative_sample", "DRAFT_TAG", "ACCEPT_TAG",
           "FINAL_TAG"]
