"""Cluster-scale serving under failure: replicated engines behind a
health-aware router, with fault injection and graceful degradation.

Everything below the cluster is the existing single-replica stack — N
independent :class:`~paddle_tpu.serving.engine.LLMEngine` replicas, each
with its own paged KV pool, scheduler, and metrics. This module owns the
fleet layer a million-user front door actually needs:

- **Routing** — session-affinity first (a session's requests keep
  landing on one replica while it stays admittable, so its prefix-cache
  chains keep hitting), then power-of-two-choices admission: two
  candidate replicas drawn from a seeded stream, the request goes to
  the healthier one. Health comes from each replica's
  ``metrics_snapshot()``: queue depth/age, KV watermark pressure
  (demand utilization, pinned cache excluded), degradation level, and
  the cluster-observed consecutive-step latency multiplier.
- **Lifecycle state machine** — ``HEALTHY -> DEGRADED -> DRAINING ->
  DOWN -> RECOVERING``: DEGRADED tracks the replica's degradation
  ladder (hysteretic, see below); DRAINING freezes admission and
  requeues waiting work while running rows finish; DOWN discards the
  engine entirely; RECOVERING warms a fresh engine for
  ``recovery_steps`` rounds before taking traffic again. Every
  transition is timestamped, so time-in-state is reportable.
- **Retry-with-backoff** — requests on a failed/drained replica are
  requeued to a survivor (re-prefill rides the normal admission path,
  hitting the survivor's prefix-hash cache when a cohort mate warmed
  it). Each requeue burns one unit of the request's ``retry_budget``
  and waits an exponential backoff before redispatch; an exhausted
  budget converts to a STRUCTURED shed (``finish_reason
  "retries_exhausted"``) instead of a hang. Duplicate finalization is
  impossible by construction: a replica's outputs are only absorbed
  while it is the request's CURRENT assignment, and terminal cluster
  outputs never regress.
- **Fault injection** — a :class:`~paddle_tpu.serving.faults.
  FaultSchedule` fires crash / drain / slowdown / kv-pressure / flaky
  / transfer-slow / transfer-drop events at virtual-clock step
  boundaries (serving/faults.py), so fleet-level robustness claims are
  reproducible chip-free: the same seed reproduces the same crashes,
  requeues, and report bytes.
- **Disaggregated prefill/decode serving** — ``roles=`` splits the
  fleet into a PREFILL pool and a DECODE pool joined by a
  page-granular KV fabric (serving/fabric.py). New requests route to
  the prefill pool; once a request's prompt is committed and its first
  token sampled, its KV pages stream to a decode replica (session
  affinity, power-of-two otherwise) and its prefill row slot frees
  IMMEDIATELY — a 32k-token prompt never again pins a slot through its
  whole decode. Chunked-prefill boundaries stream pages ahead, so the
  final handoff only bills the last chunk. Token identity survives the
  split by the same argument as retries: draws are pure functions of
  (seed, position). A fleet-scope hysteresis rung
  (:class:`FleetDegradation`) collapses routing back to colocated when
  either pool empties or the fabric saturates — counted and
  flight-recorded, never a hang — and restores when pressure clears.

Token identity under failure: every replica is built with the SAME
engine seed, so a request's sampling streams
(models/generation.request_keys) are identical wherever it lands; a
retried request re-prefills from scratch on its new replica and
regenerates the SAME tokens (greedy trivially, sampled because draws are
pure functions of (seed, generation position)). The kill-one-of-three
acceptance gate (tests/test_cluster.py) compares a faulted cluster run
token-for-token against a fault-free single engine.

The **graceful-degradation ladder** (:class:`DegradationLadder`) lives
inside each replica: under sustained watermark/queue pressure it sheds
optional work one rung at a time — (1) disable speculative decoding,
(2) shrink the decode burst to per-token, (3) evict pinned prefix
chains, (4) tighten admission (high watermark down to the low line,
one prefill per step) — and restores rung by rung, hysteretically, when
pressure clears. Every transition lands on the engine's own metrics
(``degradation_escalations`` / ``degradation_restorations`` counters,
``degradation_level`` gauge), so the loadgen report can show exactly
what service level a flash crowd cost.
"""
from __future__ import annotations

import enum
import itertools
import os
import random
import time
from collections import deque
from dataclasses import dataclass, field

from .engine import LLMEngine, Request, RequestOutput, RequestRejected
from .faults import FaultSchedule, InjectedFault
from .kv_cache import PoolExhausted


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"      # serving, but the ladder shed optional work
    DRAINING = "draining"      # no admissions; running rows finish
    DOWN = "down"              # engine discarded; requests requeued
    RECOVERING = "recovering"  # fresh engine warming, not yet routable


#: states whose engine steps run each cluster round
ACTIVE_STATES = (ReplicaState.HEALTHY, ReplicaState.DEGRADED,
                 ReplicaState.DRAINING)
#: states the router may assign new work to
ADMITTABLE_STATES = (ReplicaState.HEALTHY, ReplicaState.DEGRADED)

#: per-replica lifetime counters the cluster report needs to survive a
#: crash (an engine dies with its ServingMetrics — these are folded into
#: the replica's carry dict before the engine is discarded)
_CARRIED_COUNTERS = ("tokens_generated", "finished_requests", "prefills",
                     "preemptions", "shed_requests", "deadline_aborts",
                     "nonfinite_rows", "degradation_escalations",
                     "degradation_restorations", "host_dispatches",
                     "flight_dumps",
                     # persistence (io/persist.py): how often this
                     # replica's restores degraded, warm-reloaded
                     # chains, and persisted pin-set snapshots — a
                     # crashed engine's warm-restart story must survive
                     # into the fleet report like every other counter
                     "restore_fallbacks", "prefix_chains_restored",
                     "prefix_store_saves",
                     # two-tier KV (serving/kv_tier.py): a crashed
                     # replica's spill/prefetch story must survive into
                     # the fleet report like every other counter
                     "kv_spills", "kv_prefetch_hits",
                     "kv_prefetch_stalls",
                     # disaggregated serving (serving/fabric.py): pages
                     # landed here, handoffs the fabric refused, and
                     # fleet-store prefix hits — the disagg story of a
                     # crashed replica survives like every other counter
                     "kv_pages_transferred", "transfer_stalls",
                     "fleet_prefix_hits")


class DegradationLadder:
    """Hysteretic pressure response: shed optional work rung by rung.

    ``observe()`` runs once per engine step. ``engage_after``
    consecutive pressured steps climb one rung; ``restore_after``
    consecutive calm steps descend one — so the ladder neither flaps at
    the watermark line nor restores into the same pressure that
    engaged it. Rungs, in shed order (restore is the exact reverse):

    1. ``spec_off`` — disable speculative decoding (drops the draft
       model's launches; the verification executable is untouched).
    2. ``burst_shrink`` — collapse the on-device burst to per-token
       (latency quantization gone; admission/shed decisions regain
       per-step granularity under load).
    3. ``pinned_evict`` — evict every pinned prefix chain and zero the
       pin budget (cache yields its pages to demand).
    4. ``admission_tight`` — pull the pool's high watermark down to the
       low line and admit at most one prefill per step.

    Every transition increments ``degradation_escalations`` /
    ``degradation_restorations`` and moves the ``degradation_level``
    gauge on the ENGINE's own metrics, so single-engine operators and
    the cluster report read the same signals.
    """

    RUNGS = ("spec_off", "burst_shrink", "pinned_evict", "admission_tight")

    def __init__(self, engine: LLMEngine, *, engage_after=3,
                 restore_after=8, queue_age_slo_s=None):
        if engage_after < 1 or restore_after < 1:
            raise ValueError("engage_after/restore_after must be >= 1")
        self.engine = engine
        self.engage_after = int(engage_after)
        self.restore_after = int(restore_after)
        #: optional queue-age pressure source: the oldest waiter sitting
        #: longer than this reads as pressure even below the watermark
        self.queue_age_slo_s = queue_age_slo_s
        self.level = 0
        self._hot = 0
        self._cool = 0
        self._saved: dict = {}

    def pressure(self) -> bool:
        eng = self.engine
        if eng.pool.above_high_watermark():
            return True
        if self.queue_age_slo_s is not None and \
                eng.scheduler.max_queue_wait() > self.queue_age_slo_s:
            return True
        return False

    def observe(self):
        """One hysteresis tick; call after each engine step."""
        if self.pressure():
            self._hot += 1
            self._cool = 0
            if self._hot >= self.engage_after and \
                    self.level < len(self.RUNGS):
                self._engage(self.RUNGS[self.level])
                self.level += 1
                self._hot = 0
                self.engine.metrics.degradation_escalations.inc()
                self.engine.metrics.degradation_level.set(self.level)
                self.engine.record_fleet_event(
                    "degradation", direction="engage",
                    rung=self.RUNGS[self.level - 1], level=self.level)
        else:
            self._cool += 1
            self._hot = 0
            if self._cool >= self.restore_after and self.level > 0:
                self.level -= 1
                self._restore(self.RUNGS[self.level])
                self._cool = 0
                self.engine.metrics.degradation_restorations.inc()
                self.engine.metrics.degradation_level.set(self.level)
                self.engine.record_fleet_event(
                    "degradation", direction="restore",
                    rung=self.RUNGS[self.level], level=self.level)

    def _engage(self, rung: str):
        eng = self.engine
        if rung == "spec_off":
            self._saved[rung] = eng.spec_enabled
            eng.spec_enabled = False
        elif rung == "burst_shrink":
            self._saved[rung] = eng.burst_tokens
            eng.burst_tokens = 1
        elif rung == "pinned_evict":
            self._saved[rung] = eng.pool.pinned_page_budget
            for cid in list(eng.pool._pins):
                eng.pool.unpin(cid)
                eng.pool.pin_evictions += 1
            eng._pinned_index.clear()
            eng.pool.pinned_page_budget = 0
        elif rung == "admission_tight":
            self._saved[rung] = (eng.pool.high_watermark,
                                 eng.scheduler.config.max_prefills_per_step)
            eng.pool.high_watermark = eng.pool.low_watermark
            eng.scheduler.config.max_prefills_per_step = 1

    def _restore(self, rung: str):
        eng = self.engine
        if rung == "spec_off":
            eng.spec_enabled = self._saved.pop(rung)
        elif rung == "burst_shrink":
            eng.burst_tokens = self._saved.pop(rung)
        elif rung == "pinned_evict":
            # the evicted chains are gone (cache, not demand) — only the
            # budget comes back, and traffic repopulates it
            eng.pool.pinned_page_budget = self._saved.pop(rung)
        elif rung == "admission_tight":
            hw, mpps = self._saved.pop(rung)
            eng.pool.high_watermark = hw
            eng.scheduler.config.max_prefills_per_step = mpps


class FleetDegradation:
    """The FLEET-scope rung of the degradation ladder: collapse
    disaggregated routing back to colocated under sustained pressure.

    The per-engine :class:`DegradationLadder` rungs are untouched (they
    shed per-replica work); this guard watches fleet-level disagg
    health once per cluster round — an empty admittable prefill or
    decode pool, or fabric back-pressure (depth refusals) — and, after
    ``engage_after`` consecutive pressured rounds, COLLAPSES: the
    router ignores roles (any admittable replica takes any request,
    exactly the colocated topology) and no new handoffs issue.
    In-flight transfers still land (or requeue as fresh retries when
    their destination died) — collapse is a routing decision, never a
    hang. ``restore_after`` consecutive calm rounds restore
    disaggregated routing. Both directions count
    (``collapses``/``collapse_restores``) and flight-record, the same
    observability contract as the per-engine rungs."""

    def __init__(self, *, engage_after=3, restore_after=8):
        if engage_after < 1 or restore_after < 1:
            raise ValueError("engage_after/restore_after must be >= 1")
        self.engage_after = int(engage_after)
        self.restore_after = int(restore_after)
        self.collapsed = False
        self._hot = 0
        self._cool = 0

    def observe(self, pressured: bool) -> str | None:
        """One hysteresis tick; returns "collapse"/"restore" on a
        transition, None otherwise."""
        if pressured:
            self._hot += 1
            self._cool = 0
            if not self.collapsed and self._hot >= self.engage_after:
                self.collapsed = True
                self._hot = 0
                return "collapse"
        else:
            self._cool += 1
            self._hot = 0
            if self.collapsed and self._cool >= self.restore_after:
                self.collapsed = False
                self._cool = 0
                return "restore"
        return None


@dataclass
class _Replica:
    """Cluster-side state of one engine replica."""
    rid: int
    engine: LLMEngine | None
    ladder: DegradationLadder | None
    #: disaggregated serving pool membership: "prefill" / "decode", or
    #: None in the colocated (default) topology
    role: str | None = None
    state: ReplicaState = ReplicaState.HEALTHY
    state_since: float = 0.0
    state_time: dict = field(default_factory=dict)
    health: dict = field(default_factory=dict)
    #: when ``health`` was last refreshed from a LIVE engine (fleet
    #: clock) — the staleness stamp: a DOWN/RECOVERING replica's last
    #: health read must never masquerade as a current one
    health_at: float = 0.0
    #: bumped every time a FRESH engine is installed (crash recovery):
    #: the telemetry scraper keys counter-reset handling and histogram
    #: carry-folding off this, never off object identity
    generation: int = 0
    #: autoscale scale-down marker: a decommissioned replica drains,
    #: folds its counters, and stays DOWN — it is no longer provisioned
    #: capacity and never recovers
    decommissioned: bool = False
    steps: int = 0
    slow_multiplier: float = 1.0
    slow_until: float | None = None
    _slow_credit: float = 0.0
    drain_until: float | None = None
    flaky_until: float | None = None
    ballast_until: float | None = None
    recover_at: float | None = None
    recover_steps_left: int = 0
    consecutive_flaky: int = 0
    #: lifetime counters folded in from engines this replica lost
    carried: dict = field(default_factory=dict)

    def counter(self, name: str) -> int:
        v = self.carried.get(name, 0)
        if self.engine is not None:
            v += getattr(self.engine.metrics, name).value
        return v

    @property
    def ballast_id(self) -> str:
        return f"__fault_ballast_{self.rid}__"


class ClusterEngine:
    """N ``LLMEngine`` replicas behind a health-aware router.

    Drives like an engine: ``add_request`` routes (or parks, when no
    replica is admittable), ``step()`` runs one cluster round — fault
    events, state transitions, retry redispatch, one engine step per
    active replica — and returns the touched cluster-level
    ``RequestOutput``\\ s. ``paddle_tpu.loadgen.ClusterDriver`` replays
    workload traces against it on one virtual clock.
    """

    def __init__(self, model, num_replicas=2, *, seed=0,
                 now_fn=time.monotonic, retry_budget=2,
                 retry_backoff_s=0.02, session_affinity=True,
                 recovery_steps=2, crash_after_flaky=3,
                 crash_recover_s=None, faults: FaultSchedule | None = None,
                 ladder=True, ladder_kw=None, tracer=None,
                 flight_capacity=256, prefix_store=None, roles=None,
                 transfer_model=None, fabric_depth=4,
                 fleet_prefix_cache=None, collapse_after=3,
                 collapse_restore_after=8, **engine_kw):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, "
                             f"got {num_replicas}")
        if retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")
        # disaggregated serving: roles=("prefill", ..., "decode", ...)
        # splits the fleet; None (the default) is the colocated topology
        # and leaves EVERY code path — including the seeded router
        # stream — byte-identical to a cluster without this feature
        if roles is not None:
            roles = tuple(str(r) for r in roles)
            if len(roles) != num_replicas:
                raise ValueError(
                    f"roles has {len(roles)} entries for {num_replicas} "
                    f"replicas")
            bad = [r for r in roles if r not in ("prefill", "decode")]
            if bad:
                raise ValueError(
                    f"roles must be 'prefill' or 'decode', got {bad}")
            if "prefill" not in roles or "decode" not in roles:
                raise ValueError(
                    "disaggregated serving needs at least one prefill "
                    "AND one decode replica")
        self._roles = roles
        self.num_replicas = num_replicas
        self._now = now_fn
        self.retry_budget = int(retry_budget)
        self.retry_backoff_s = float(retry_backoff_s)
        self.session_affinity = session_affinity
        self.recovery_steps = int(recovery_steps)
        self.crash_after_flaky = int(crash_after_flaky)
        #: DOWN -> RECOVERING delay for UNSCHEDULED crashes (a real
        #: engine exception or a flaky escalation); scheduled crash
        #: events carry their own recover_s. None = stays down.
        self.crash_recover_s = crash_recover_s
        self._model = model
        self._seed = seed
        # fleet observability (serving/tracing.py): ONE always-on
        # flight recorder shared by every replica engine (their step/
        # abort entries interleave with router/fault/crash entries on
        # the one clock — the "last N steps of fleet events" a crash
        # dump replays), and an optional shared tracer so a request's
        # spans follow it ACROSS replicas (enqueue on replica 0, crash,
        # retry hop, re-prefill on replica 2 — one timeline).
        from .tracing import FlightRecorder
        self.tracer = tracer
        self.flight = FlightRecorder(flight_capacity)
        self._engine_kw = dict(engine_kw)
        self._engine_kw["tracer"] = tracer
        self._engine_kw["flight_recorder"] = self.flight
        # persistent prefix store (io/persist.py): ONE ArtifactStore
        # shared by every replica (and every RECOVERY rebuild), wired
        # to the fleet flight recorder — a path becomes a store here so
        # storage fallbacks land in the fleet post-mortem ring, and a
        # crashed replica's successor warm-reloads the chains its
        # predecessor (or any cohort-mate replica) persisted.
        if prefix_store is not None:
            if isinstance(prefix_store, (str, os.PathLike)):
                from ..io.persist import ArtifactStore
                prefix_store = ArtifactStore(
                    prefix_store, flight_recorder=self.flight,
                    now_fn=self._now)
            self._engine_kw["prefix_store"] = prefix_store
        self.prefix_store = self._engine_kw.get("prefix_store")
        # disaggregated serving plumbing (serving/fabric.py): the KV
        # fabric, the fleet-wide prefix cache, and the collapse guard
        # only exist in roles mode — the colocated default constructs
        # none of them and consumes no extra seeded-RNG draws
        self.fabric = None
        self.fleet_prefix = None
        self._collapse_guard = None
        self.disagg_counters = {k: 0 for k in (
            "handoffs", "transfer_drops", "transfer_requeues",
            "collapses", "collapse_restores", "transfer_slow_faults",
            "transfer_drop_faults")}
        self._pending_injections: deque = deque()
        self._decode_affinity: dict[object, int] = {}
        self._round_disagg_pressure = False
        if roles is not None:
            from .fabric import FleetPrefixCache, KVFabric
            self.fabric = KVFabric(transfer_model, depth=fabric_depth)
            if fleet_prefix_cache is None or fleet_prefix_cache is True:
                # default ON in roles mode: a prompt prefilled anywhere
                # in the fleet is never re-prefilled anywhere — backed
                # by the shared ArtifactStore when one exists (chains
                # survive replica crashes), memory-backed otherwise
                fleet_prefix_cache = FleetPrefixCache(
                    store=self.prefix_store)
            self.fleet_prefix = fleet_prefix_cache
            self._engine_kw["fleet_prefix_cache"] = self.fleet_prefix
            self._collapse_guard = FleetDegradation(
                engage_after=collapse_after,
                restore_after=collapse_restore_after)
        elif fleet_prefix_cache:
            # colocated fleets may still opt into the shared cache
            # (cross-replica warm prefixes without disaggregation)
            from .fabric import FleetPrefixCache
            if fleet_prefix_cache is True:
                fleet_prefix_cache = FleetPrefixCache(
                    store=self.prefix_store)
            self.fleet_prefix = fleet_prefix_cache
            self._engine_kw["fleet_prefix_cache"] = self.fleet_prefix
        self._ladder_on = ladder
        self._ladder_kw = dict(ladder_kw or {})
        #: seeded router stream: power-of-two-choices candidate draws
        #: are the cluster's ONLY randomness, and it is deterministic
        self._rng = random.Random(seed)
        #: fault script + private read cursor (the schedule is immutable)
        self._fault_events = tuple(faults) if faults is not None else ()
        self._fault_cursor = 0
        self.faults = faults
        self.counters = {k: 0 for k in (
            "retries", "retry_budget_sheds", "fleet_unavailable_sheds",
            "crashes", "recoveries", "drains", "flaky_steps",
            "engine_errors", "router_decisions", "affinity_hits",
            "state_transitions", "kv_pressure_faults", "slowdown_faults",
            "flight_dumps", "scale_ups", "scale_downs")}
        now = self._now()
        self.replicas = [
            self._new_replica(i, now,
                              roles[i] if roles is not None else None)
            for i in range(num_replicas)]
        self._requests: dict[str, Request] = {}
        self._meta: dict[str, dict] = {}
        self._outputs: dict[str, RequestOutput] = {}
        #: insertion-ordered unfinished-request index (dict, NOT set:
        #: str-set iteration order is hash-randomized per process and
        #: crash-victim requeue order must stay byte-reproducible) —
        #: keeps has_unfinished()/crash scans O(live), not O(ever)
        self._unfinished: dict[str, None] = {}
        self._affinity: dict[object, int] = {}
        self._parked: deque[str] = deque()
        self._ids = itertools.count()

    # ------------------------------------------------------------------
    # replica construction / health
    # ------------------------------------------------------------------
    def _new_engine(self, rid=None, role=None) -> LLMEngine:
        # every replica gets the SAME engine seed: a request's sampling
        # streams are pure functions of (engine seed, request seed,
        # position), so a retry on another replica regenerates the same
        # tokens — the cross-replica token-identity contract
        kw = self._engine_kw
        if role == "decode" and not kw.get("host_kv_pages"):
            # decode replicas default to a two-tier pool: transferred
            # pages land in the host arena as PARKED sequences and ride
            # the cursor-ahead prefetch path into HBM — the fabric's
            # staging buffer. An explicit host_kv_pages overrides.
            kw = dict(kw, host_kv_pages=64)
        return LLMEngine(self._model, now_fn=self._now, seed=self._seed,
                         engine_id=rid, **kw)

    def _new_replica(self, rid: int, now: float, role=None) -> _Replica:
        eng = self._new_engine(rid, role)
        ladder = DegradationLadder(eng, **self._ladder_kw) \
            if self._ladder_on else None
        rep = _Replica(rid=rid, engine=eng, ladder=ladder, role=role,
                       state=ReplicaState.HEALTHY, state_since=now)
        rep.health = self._health_of(rep)
        rep.health_at = now
        return rep

    def _health_of(self, rep: _Replica) -> dict:
        """Router health view — the same four signals the replica's
        ``metrics_snapshot()`` gauges expose, read straight off the live
        scheduler/ladder (this runs per replica per cluster round; the
        full snapshot sorts every latency reservoir, far too heavy for
        the routing hot path), plus cluster-side observations
        (consecutive-step latency)."""
        eng = rep.engine
        pool = eng.pool
        demand = (pool.used_pages - pool.evictable_pages) / pool.capacity
        return {
            "queue_depth": int(eng.scheduler.queue_depth()),
            "running": len(eng.scheduler.running),
            "queue_age_s": float(eng.scheduler.max_queue_wait()),
            "kv_pressure": demand,
            "degradation_level": rep.ladder.level
            if rep.ladder is not None else 0,
            "step_latency_x": rep.slow_multiplier,
        }

    @staticmethod
    def _score(rep: _Replica) -> float:
        """Lower = healthier. Queue length dominates (it IS expected
        wait in steps); pressure, degradation, and latency inflation
        push a sick replica's score up before its queue shows it. The
        latency multiplier reads the LIVE cluster observation, not the
        snapshot taken at the replica's last step — a replica slowed a
        moment ago must lose the very next coin flip."""
        h = rep.health
        return (h["queue_depth"] + h["running"]
                + 8.0 * h["kv_pressure"]
                + 2.0 * h["degradation_level"]
                + 4.0 * (rep.slow_multiplier - 1.0)
                + h["queue_age_s"])

    def _set_state(self, rep: _Replica, state: ReplicaState, now: float):
        if state is rep.state:
            return
        old = rep.state
        rep.state_time[old.value] = rep.state_time.get(old.value, 0.0) \
            + (now - rep.state_since)
        rep.state = state
        rep.state_since = now
        self.counters["state_transitions"] += 1

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def _candidates(self) -> list:
        cands = [r for r in self.replicas
                 if r.state in ADMITTABLE_STATES and r.engine is not None]
        if self._roles is not None and not self.collapsed:
            # stage-1 routing: new prompts go to the PREFILL pool. An
            # empty admittable prefill pool falls back to the whole
            # fleet (a per-dispatch mini-collapse — better served
            # colocated than parked) and reads as collapse pressure.
            pf = [r for r in cands if r.role == "prefill"]
            if pf:
                return pf
            self._round_disagg_pressure = True
        return cands

    @property
    def collapsed(self) -> bool:
        """True while the fleet rung has disaggregation collapsed to
        colocated routing (always False outside roles mode)."""
        return self._collapse_guard is not None \
            and self._collapse_guard.collapsed

    def _route_decode(self, rid: str):
        """Stage-2 routing: pick the decode replica a finished prefill
        hands its KV pages to. Session affinity first (the session's
        decode rows share a replica, so ITS prefix chains and forks
        stay warm), then power-of-two-choices over the same seeded
        stream as stage 1. None when no decode replica is admittable —
        the request simply keeps decoding on its prefill replica
        (correctness never depends on the handoff happening)."""
        cands = [r for r in self.replicas
                 if r.state in ADMITTABLE_STATES and r.engine is not None
                 and r.role == "decode"]
        if not cands:
            self._round_disagg_pressure = True
            return None
        session = self._meta[rid]["session"]
        if self.session_affinity and session is not None:
            aff = self._decode_affinity.get(session)
            for r in cands:
                if r.rid == aff:
                    self.counters["affinity_hits"] += 1
                    return r
        if len(cands) == 1:
            pick = cands[0]
        else:
            i, j = self._rng.sample(range(len(cands)), 2)
            pick = min(cands[i], cands[j],
                       key=lambda r: (self._score(r), r.rid))
        self.counters["router_decisions"] += 1
        if session is not None:
            self._decode_affinity[session] = pick.rid
        return pick

    def _route(self, rid: str):
        """Pick a replica for ``rid``: session affinity if its pinned
        replica is still admittable, else power-of-two-choices over the
        seeded stream. Returns None when no replica is admittable."""
        cands = self._candidates()
        if not cands:
            return None
        meta = self._meta[rid]
        session = meta["session"]
        if self.session_affinity and session is not None:
            aff = self._affinity.get(session)
            for r in cands:
                if r.rid == aff:
                    self.counters["affinity_hits"] += 1
                    return r
        if len(cands) == 1:
            pick = cands[0]
        else:
            i, j = self._rng.sample(range(len(cands)), 2)
            # score ties break on rid so the choice is total
            pick = min(cands[i], cands[j],
                       key=lambda r: (self._score(r), r.rid))
        self.counters["router_decisions"] += 1
        if session is not None:
            self._affinity[session] = pick.rid
        return pick

    def _dispatch(self, rid: str, touched: dict | None) -> bool:
        """Hand ``rid`` to a routed replica. Returns False when no
        replica is admittable (the request stays parked). An oversize
        rejection finalizes the cluster output (and re-raises only when
        called synchronously from ``add_request`` — ``touched`` is the
        step-time signal)."""
        rep = self._route(rid)
        if rep is None:
            return False
        req = self._requests[rid]
        meta = self._meta[rid]
        now = self._now()
        # SLOs are anchored on the request's FIRST cluster arrival: a
        # retry gets the REMAINING window, not a fresh one — the client
        # started waiting when it first asked
        deadline_s = None if req.deadline_s is None else \
            max(req.deadline_s - (now - meta["arrival"]), 0.0)
        abort_after_s = None if req.abort_after_s is None else \
            max(req.abort_after_s - (now - meta["arrival"]), 0.0)
        try:
            rep.engine.add_request(
                req.prompt_token_ids, max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed,
                eos_token_id=req.eos_token_id, deadline_s=deadline_s,
                abort_after_s=abort_after_s, request_id=rid,
                tenant_id=req.tenant_id, adapter_id=req.adapter_id)
        except RequestRejected:
            out = self._outputs[rid]
            out.status = "aborted"
            out.finish_reason = "rejected_oversize"
            self._unfinished.pop(rid, None)
            if touched is None:
                raise
            touched[rid] = out
            return True
        except ValueError:
            # engine-side parameter validation (empty prompt, bad
            # max_new_tokens/top_k/top_p, ...): finalize the cluster
            # output so the fleet never carries a permanently-unfinished
            # request — and, like RequestRejected, re-raise only on the
            # synchronous add_request path. A parked invalid request
            # reaching here from _redispatch becomes a structured abort
            # instead of detonating the whole cluster round.
            out = self._outputs[rid]
            out.status = "aborted"
            out.finish_reason = "invalid_request"
            self._unfinished.pop(rid, None)
            if touched is None:
                raise
            touched[rid] = out
            return True
        meta["replica"] = rep.rid
        if self.tracer is not None:
            self.tracer.span(rid, "dispatch", now, replica=rep.rid,
                             retry=meta["retries"])
        out = self._outputs[rid]
        if out.status == "pending":
            out.status = "waiting"
        if touched is not None:
            touched[rid] = out
        return True

    # ------------------------------------------------------------------
    # public API (mirrors LLMEngine)
    # ------------------------------------------------------------------
    def add_request(self, prompt_token_ids, *, max_new_tokens=16,
                    temperature=0.0, top_k=None, top_p=None, seed=None,
                    eos_token_id=None, deadline_s=None, abort_after_s=None,
                    request_id=None, session_id=None, tenant_id=None,
                    adapter_id=None):
        """Queue a request with the fleet; returns its id. Routes
        immediately when a replica is admittable, otherwise parks until
        one is. ``session_id`` opts the request into session affinity
        (a cohort's shared-prefix traffic stays on one replica's warm
        prefix cache). Raises :class:`RequestRejected` (after recording
        a finalized aborted output) exactly like ``LLMEngine``."""
        prompt = [int(t) for t in prompt_token_ids]
        rid = request_id or f"creq-{next(self._ids)}"
        if rid in self._requests:
            raise KeyError(f"duplicate request_id {rid!r}")
        self._requests[rid] = Request(
            prompt_token_ids=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            eos_token_id=eos_token_id, deadline_s=deadline_s,
            abort_after_s=abort_after_s, request_id=rid,
            tenant_id=tenant_id, adapter_id=adapter_id)
        self._meta[rid] = {"retries": 0, "session": session_id,
                           "replica": None, "arrival": self._now(),
                           "not_before": None, "preempt_base": 0}
        self._outputs[rid] = RequestOutput(rid, prompt, status="pending")
        self._unfinished[rid] = None
        if not self._dispatch(rid, None):
            self._parked.append(rid)
            if self.tracer is not None:
                self.tracer.span(rid, "park", self._now())
        return rid

    def request_retries(self, request_id) -> int:
        return self._meta[request_id]["retries"]

    def has_unfinished(self) -> bool:
        return bool(self._unfinished)

    def outputs(self) -> dict:
        return dict(self._outputs)

    def live_pools(self):
        """[(replica id, PagedKVPool)] of every replica holding an
        engine — the loadgen driver's per-step invariant-audit surface."""
        return [(r.rid, r.engine.pool) for r in self.replicas
                if r.engine is not None]

    # ------------------------------------------------------------------
    # autoscaling (paddle_tpu.telemetry.autoscale drives this)
    # ------------------------------------------------------------------
    def provisioned_replicas(self) -> int:
        """Replicas that count as capacity: everything not
        decommissioned (a crashed-but-recovering replica is still
        provisioned — the autoscaler must not double-provision around
        a transient crash)."""
        return sum(1 for r in self.replicas if not r.decommissioned)

    def scale_to(self, n: int) -> list:
        """Grow or shrink the fleet to ``n`` provisioned replicas — the
        chip-free autoscaling exerciser (``ClusterDriver`` applies the
        telemetry policy's ``desired_replicas`` through this between
        rounds).

        Growing appends fresh HEALTHY replicas (new rids — dead slots
        are never reused, so fault scripts and telemetry series keep
        their addressing). Shrinking decommissions the highest-rid
        provisioned replicas: waiting work is requeued to survivors
        immediately (the drain discipline), running rows finish in
        place, and the replica then folds its counters and goes DOWN
        for good. Returns the cluster ``RequestOutput``\\ s the requeues
        touched (terminal sheds included), so a driver can absorb them
        without waiting for the next round."""
        n = int(n)
        if n < 1:
            raise ValueError(f"scale_to needs n >= 1, got {n}")
        now = self._now()
        touched: dict[str, RequestOutput] = {}
        provisioned = [r for r in self.replicas if not r.decommissioned]
        if n > len(provisioned):
            for _ in range(n - len(provisioned)):
                rid = len(self.replicas)
                # roles mode: scale-ups join the DECODE pool (decode
                # capacity is what tracks load; prefill slots recycle)
                self.replicas.append(self._new_replica(
                    rid, now,
                    "decode" if self._roles is not None else None))
                self.counters["scale_ups"] += 1
                self.flight.record("scale_up", now, replica=rid)
                if self.tracer is not None:
                    self.tracer.event("scale_up", now, replica=rid)
        elif n < len(provisioned):
            for rep in sorted(provisioned, key=lambda r: -r.rid)[
                    :len(provisioned) - n]:
                self._decommission(rep, now, touched)
        self.num_replicas = self.provisioned_replicas()
        return list(touched.values())

    def _decommission(self, rep: _Replica, now: float, touched: dict):
        self.counters["scale_downs"] += 1
        rep.decommissioned = True
        self.flight.record("scale_down", now, replica=rep.rid)
        if self.tracer is not None:
            self.tracer.event("scale_down", now, replica=rep.rid)
        if rep.engine is None:
            # already DOWN (crashed): just cancel any pending recovery
            rep.recover_at = None
            self._set_state(rep, ReplicaState.DOWN, now)
            return
        self._set_state(rep, ReplicaState.DRAINING, now)
        rep.drain_until = None          # ends on empty, not on a clock
        rep.engine.scheduler.admission_blocked = True
        waiting_ids = [s.seq_id for s in rep.engine.scheduler.waiting]
        for rid in waiting_ids:
            if rid in self._meta and rep.engine.withdraw(rid):
                self._meta[rid]["replica"] = None
                self._requeue(rid, now, touched, from_replica=rep.rid)
        if not rep.engine.has_unfinished():
            self._fold_counters(rep)
            rep.engine = None
            rep.ladder = None
            self._set_state(rep, ReplicaState.DOWN, now)

    # ------------------------------------------------------------------
    # the cluster round
    # ------------------------------------------------------------------
    def step(self):
        """One cluster round: fire due fault events, tick the state
        machine, redispatch parked/retried requests, then one engine
        step per active replica (slowdown-gated), absorbing each
        replica's touched outputs into the cluster view. Returns the
        touched cluster ``RequestOutput``\\ s."""
        now = self._now()
        touched: dict[str, RequestOutput] = {}
        self._apply_faults(now, touched)
        self._tick_states(now)
        if self.fabric is not None:
            self._land_transfers(now, touched)
        self._redispatch(now, touched)
        for rep in self.replicas:
            if rep.state not in ACTIVE_STATES or rep.engine is None:
                continue
            # slowdown gate: a replica at multiplier m executes one
            # engine step every m cluster rounds — its consecutive-step
            # latency IS m * step_time, which is what health scores see
            rep._slow_credit += 1.0
            if rep._slow_credit + 1e-9 < rep.slow_multiplier:
                continue
            rep._slow_credit -= rep.slow_multiplier
            try:
                if rep.flaky_until is not None and now < rep.flaky_until:
                    rep.consecutive_flaky += 1
                    self.counters["flaky_steps"] += 1
                    raise InjectedFault(
                        f"injected flaky step on replica {rep.rid}")
                outs = rep.engine.step()
                rep.consecutive_flaky = 0
            except InjectedFault:
                if rep.consecutive_flaky >= self.crash_after_flaky:
                    # persistent flakiness IS a crash: requeue and rebuild
                    self._crash(rep, now, self.crash_recover_s, touched)
                continue
            except Exception:
                # a real engine failure: the fleet must survive it —
                # treat as an unscheduled crash (requests requeued)
                self.counters["engine_errors"] += 1
                self._crash(rep, now, self.crash_recover_s, touched)
                continue
            rep.steps += 1
            if rep.ladder is not None:
                rep.ladder.observe()
            rep.health = self._health_of(rep)
            rep.health_at = now
            if rep.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
                degraded = rep.ladder.level > 0 if rep.ladder is not None \
                    else rep.engine.pool.above_high_watermark()
                self._set_state(
                    rep, ReplicaState.DEGRADED if degraded
                    else ReplicaState.HEALTHY, now)
            for out in outs:
                self._absorb(rep, out, touched)
            if self.fabric is not None and rep.role == "prefill" \
                    and not self.collapsed:
                self._handoffs(rep, now)
        if self._collapse_guard is not None:
            self._observe_collapse(now)
        return list(touched.values())

    def run(self, max_steps=None):
        """Drive step() until every request resolves; returns outputs."""
        steps = 0
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"cluster did not drain within {max_steps} steps")
        return self.outputs()

    # ------------------------------------------------------------------
    # faults / state machine
    # ------------------------------------------------------------------
    def next_fault_t(self):
        """Virtual time of the next unfired fault event (None when the
        script is exhausted) — the driver's idle-jump bound."""
        if self._fault_cursor < len(self._fault_events):
            return self._fault_events[self._fault_cursor].t
        return None

    def _apply_faults(self, now: float, touched: dict):
        while self._fault_cursor < len(self._fault_events) and \
                self._fault_events[self._fault_cursor].t <= now:
            ev = self._fault_events[self._fault_cursor]
            self._fault_cursor += 1
            if ev.replica >= len(self.replicas):
                continue
            self.flight.record("fault", now, fault=ev.kind,
                               replica=ev.replica)
            rep = self.replicas[ev.replica]
            if ev.kind == "crash":
                if rep.engine is not None:
                    self._crash(rep, now, ev.recover_s, touched)
            elif ev.kind == "transfer_slow":
                # fabric faults target the wire, not the engine — they
                # apply even while the replica's body is being rebuilt
                if self.fabric is not None:
                    self.fabric.set_slow(ev.replica, now + ev.duration_s,
                                         ev.magnitude)
                    self.disagg_counters["transfer_slow_faults"] += 1
            elif ev.kind == "transfer_drop":
                if self.fabric is not None:
                    self.fabric.set_drop(ev.replica, now + ev.duration_s)
                    self.disagg_counters["transfer_drop_faults"] += 1
            elif rep.engine is None:
                continue                      # window faults need a body
            elif ev.kind == "drain":
                self._drain(rep, now, now + ev.duration_s, touched)
            elif ev.kind == "slowdown":
                rep.slow_multiplier = float(ev.magnitude)
                rep.slow_until = now + ev.duration_s
                self.counters["slowdown_faults"] += 1
            elif ev.kind == "kv_pressure":
                self._ballast(rep, now + ev.duration_s, ev.magnitude)
            elif ev.kind == "flaky":
                rep.flaky_until = now + ev.duration_s

    def _tick_states(self, now: float):
        for rep in self.replicas:
            if rep.state is ReplicaState.DOWN:
                if rep.recover_at is not None and now >= rep.recover_at:
                    rep.engine = self._new_engine(rep.rid, rep.role)
                    # fresh engine, fresh counters: the generation bump
                    # is what tells the telemetry scraper to treat the
                    # next counter readings as a reset and to fold the
                    # dead engine's histogram population into the carry
                    rep.generation += 1
                    rep.ladder = DegradationLadder(
                        rep.engine, **self._ladder_kw) \
                        if self._ladder_on else None
                    rep.health = self._health_of(rep)
                    rep.health_at = now
                    rep.recover_at = None
                    rep.recover_steps_left = self.recovery_steps
                    rep.consecutive_flaky = 0
                    rep.slow_multiplier = 1.0
                    rep.slow_until = rep.flaky_until = None
                    rep.ballast_until = None
                    self._set_state(rep, ReplicaState.RECOVERING, now)
            elif rep.state is ReplicaState.RECOVERING:
                rep.recover_steps_left -= 1
                if rep.recover_steps_left <= 0:
                    self.counters["recoveries"] += 1
                    self._set_state(rep, ReplicaState.HEALTHY, now)
            elif rep.state is ReplicaState.DRAINING:
                if rep.decommissioned:
                    # autoscale scale-down: the drain ends when the
                    # replica's running rows finish — fold its lifetime
                    # counters and release the engine for good
                    if not rep.engine.has_unfinished():
                        self._fold_counters(rep)
                        rep.engine = None
                        rep.ladder = None
                        rep.recover_at = None
                        self._set_state(rep, ReplicaState.DOWN, now)
                elif rep.drain_until is not None \
                        and now >= rep.drain_until:
                    rep.drain_until = None
                    rep.engine.scheduler.admission_blocked = False
                    self._set_state(rep, ReplicaState.HEALTHY, now)
            if rep.engine is not None:
                if rep.slow_until is not None and now >= rep.slow_until:
                    rep.slow_multiplier = 1.0
                    rep.slow_until = None
                if rep.ballast_until is not None \
                        and now >= rep.ballast_until:
                    if rep.ballast_id in rep.engine.pool:
                        rep.engine.pool.free(rep.ballast_id)
                    rep.ballast_until = None

    def _ballast(self, rep: _Replica, until: float, fraction: float):
        """KV-pressure spike: pin ``fraction`` of the replica's pool
        under a ballast allocation — watermarks, preemption, and the
        degradation ladder see real page pressure."""
        pool = rep.engine.pool
        self.counters["kv_pressure_faults"] += 1
        if rep.ballast_id in pool:
            # overlapping windows merge: the existing ballast stays and
            # the pressure extends to whichever window ends later
            rep.ballast_until = until if rep.ballast_until is None \
                else max(rep.ballast_until, until)
            return
        want = max(int(pool.capacity * fraction), 1)
        pages = min(want, pool.free_pages)
        if pages < 1:
            return                             # already at full pressure
        pool.allocate(rep.ballast_id, pages * pool.page_size)
        rep.ballast_until = until

    def _drain(self, rep: _Replica, now: float, until: float,
               touched: dict):
        self.counters["drains"] += 1
        self._set_state(rep, ReplicaState.DRAINING, now)
        rep.drain_until = until
        rep.engine.scheduler.admission_blocked = True
        self.flight.record("drain", now, replica=rep.rid)
        if self.tracer is not None:
            self.tracer.event("drain", now, replica=rep.rid)
        # waiting work will not start here for the whole window — hand
        # it to survivors now; running rows finish their drain in place
        waiting_ids = [s.seq_id for s in rep.engine.scheduler.waiting]
        for rid in waiting_ids:
            if rid in self._meta and rep.engine.withdraw(rid):
                self._meta[rid]["replica"] = None
                self._requeue(rid, now, touched, from_replica=rep.rid)

    @staticmethod
    def _fold_counters(rep: _Replica):
        """Fold a dying engine's lifetime counters into the replica's
        carry so the cluster report keeps counting across the loss —
        shared by crashes and autoscale decommissions."""
        for k in _CARRIED_COUNTERS:
            rep.carried[k] = rep.carried.get(k, 0) + \
                getattr(rep.engine.metrics, k).value

    def _crash(self, rep: _Replica, now: float, recover_s, touched: dict):
        self.counters["crashes"] += 1
        self._fold_counters(rep)
        victims = [rid for rid in self._unfinished
                   if self._meta[rid]["replica"] == rep.rid]
        rep.engine = None
        rep.ladder = None
        rep.health = {"queue_depth": 0, "running": 0, "queue_age_s": 0.0,
                      "kv_pressure": 0.0, "degradation_level": 0,
                      "step_latency_x": 1.0}
        # a decommissioned replica is no longer provisioned capacity:
        # it never recovers, whatever killed it
        rep.recover_at = None if recover_s is None or rep.decommissioned \
            else now + recover_s
        rep.drain_until = None
        self._set_state(rep, ReplicaState.DOWN, now)
        # replica crash: the canonical flight-recorder auto-dump — the
        # last-N fleet events (every replica's steps, faults, requeues)
        # leading into the crash become the post-mortem artifact
        self.flight.record("crash", now, replica=rep.rid,
                           victims=len(victims))
        self.counters["flight_dumps"] += 1
        self.flight.dump("replica_crash", t=now, replica=rep.rid,
                         victims=len(victims))
        if self.tracer is not None:
            self.tracer.event("replica_crash", now, replica=rep.rid,
                              victims=len(victims))
        for rid in victims:
            self._meta[rid]["replica"] = None
            self._requeue(rid, now, touched, from_replica=rep.rid)
        if self.fabric is not None:
            # in-flight transfers TO the dead replica lose their landing
            # pad: requeue as fresh retries. Transfers FROM it are fine
            # — their bytes were captured host-side at extraction.
            for tr in self.fabric.cancel_dst(rep.rid):
                out = self._outputs.get(tr.rid)
                if out is not None and not out.finished:
                    self.disagg_counters["transfer_requeues"] += 1
                    self._requeue(tr.rid, now, touched,
                                  from_replica=rep.rid)

    def _requeue(self, rid: str, now: float, touched: dict,
                 from_replica=None):
        """Retry-with-backoff: park the request for redispatch on a
        survivor, or convert an exhausted retry budget into a
        STRUCTURED shed — a terminal ``RequestOutput`` the client can
        reason about, never a hang."""
        meta = self._meta[rid]
        out = self._outputs[rid]
        if meta["retries"] >= self.retry_budget:
            # budget exhausted: this requeue attempt is NOT granted, so
            # it does not count as a retry — request_retries() and the
            # fleet "retries" counter agree (both count granted requeues)
            self.counters["retry_budget_sheds"] += 1
            out.status = "shed"
            out.finish_reason = "retries_exhausted"
            self._unfinished.pop(rid, None)
            if self.tracer is not None:
                self.tracer.span(rid, "shed", now,
                                 reason="retries_exhausted",
                                 from_replica=from_replica)
        else:
            meta["retries"] += 1
            self.counters["retries"] += 1
            # the new replica starts the request from scratch, but the
            # preemptions its old replicas charged already happened —
            # carry them so the report's per-request count stays lifetime
            meta["preempt_base"] = out.num_preemptions
            # exponential backoff: 1x, 2x, 4x... of the base interval —
            # a survivor absorbing a dead replica's load should not also
            # absorb its whole queue in one step
            meta["not_before"] = now + self.retry_backoff_s \
                * (2 ** (meta["retries"] - 1))
            out.status = "waiting"
            out.token_ids = []
            out.finish_reason = None
            self._parked.append(rid)
            if self.tracer is not None:
                # the cross-replica hop: retry ordinal, which replica
                # lost the request, and when the backoff releases it
                self.tracer.span(rid, "retry_hop", now,
                                 retry=meta["retries"],
                                 from_replica=from_replica,
                                 not_before=meta["not_before"])
        touched[rid] = out

    def _fleet_dead(self) -> bool:
        """True when every replica is DOWN with no recovery scheduled —
        nothing parked can EVER be placed again."""
        return all(r.state is ReplicaState.DOWN and r.recover_at is None
                   for r in self.replicas)

    def _redispatch(self, now: float, touched: dict):
        if self._parked and self._fleet_dead():
            # the whole fleet is permanently gone: converting the parked
            # queue into structured sheds is the only non-hang outcome
            # (the module contract — retry exhaustion AND fleet loss both
            # shed, never spin)
            while self._parked:
                rid = self._parked.popleft()
                out = self._outputs[rid]
                if out.finished:
                    continue
                self.counters["fleet_unavailable_sheds"] += 1
                out.status = "shed"
                out.finish_reason = "fleet_unavailable"
                self._unfinished.pop(rid, None)
                if self.tracer is not None:
                    self.tracer.span(rid, "shed", now,
                                     reason="fleet_unavailable")
                touched[rid] = out
            return
        for _ in range(len(self._parked)):
            rid = self._parked.popleft()
            out = self._outputs[rid]
            if out.finished:
                continue
            meta = self._meta[rid]
            nb = meta.get("not_before")
            if nb is not None and now < nb:
                self._parked.append(rid)       # still backing off
                continue
            if self._dispatch(rid, touched):
                meta["not_before"] = None
            else:
                self._parked.append(rid)       # nobody admittable yet
                break

    # ------------------------------------------------------------------
    # disaggregated serving: handoffs / landings / collapse rung
    # ------------------------------------------------------------------
    def _handoffs(self, rep: _Replica, now: float):
        """After a prefill replica's step: stream chunk-boundary pages
        for mid-prefill rows, and hand every caught-up row (prompt
        committed, first token sampled) to a decode replica. A refusal
        — fabric at depth — counts a ``transfer_stall`` on the source
        and the row simply keeps decoding here until a later round; a
        missing decode pool skips the handoff entirely. Neither is ever
        a hang: local decode remains correct, just colocated."""
        eng = rep.engine
        pool = eng.pool
        for seq in list(eng.scheduler.running):
            rid = seq.seq_id
            if rid not in self._meta:
                continue                 # fault ballast, not a request
            if seq.uncached_len != 1 or not seq.tokens:
                # chunked prefill in progress: the pages finished so far
                # stream ahead, so the eventual handoff bills only the
                # final chunk
                self.fabric.stream(rid, pool.pages_for(seq.cached_len))
                continue
            dst = self._route_decode(rid)
            if dst is None:
                continue
            if self.fabric.in_flight >= self.fabric.depth:
                self.fabric.counters["refusals"] += 1
                eng.metrics.transfer_stalls.inc()
                self._round_disagg_pressure = True
                continue
            pages = pool.pages_for(seq.cached_len)
            payload = eng.extract_request(rid)
            self.fabric.issue(rid, payload, src=rep.rid, dst=dst.rid,
                              pages=pages, now=now)
            self._meta[rid]["replica"] = None     # in transit
            self.disagg_counters["handoffs"] += 1

    def _land_transfers(self, now: float, touched: dict):
        """Start-of-round: transfers whose modeled latency elapsed land
        on their decode replica (plus injections deferred by a full
        pool last round). A landing whose destination died or left the
        admittable set requeues as a FRESH retry — re-prefill
        regenerates the identical tokens, so correctness never depends
        on the bytes arriving."""
        pending = list(self._pending_injections)
        self._pending_injections.clear()
        for tr in pending + self.fabric.take_ready(now):
            self._land_one(tr, now, touched)

    def _land_one(self, tr, now: float, touched: dict):
        rid = tr.rid
        out = self._outputs.get(rid)
        if out is None or out.finished:
            return                       # cancelled/shed while in flight
        if tr.dropped:
            # transfer_drop fault: the payload is lost after its modeled
            # latency — count it and requeue (recompute keeps correctness)
            self.disagg_counters["transfer_drops"] += 1
            self.flight.record("transfer_drop", now, request=rid,
                               src=tr.src, dst=tr.dst, pages=tr.pages)
            self._requeue(rid, now, touched, from_replica=tr.src)
            return
        dst = self.replicas[tr.dst]
        if dst.engine is None or dst.state not in ADMITTABLE_STATES:
            self.disagg_counters["transfer_requeues"] += 1
            self._requeue(rid, now, touched, from_replica=tr.dst)
            return
        try:
            dst.engine.inject_request(tr.payload)
        except PoolExhausted:
            # destination momentarily full: decode rows always advance,
            # so pages free — retry the injection next round
            self._pending_injections.append(tr)
            return
        except (KeyError, ValueError):
            self.disagg_counters["transfer_requeues"] += 1
            self._requeue(rid, now, touched, from_replica=tr.dst)
            return
        self._meta[rid]["replica"] = tr.dst
        if self.tracer is not None:
            # the cross-pool hop in the request's timeline: the latency
            # breakdown carves latency_s out of the decode window
            self.tracer.span(rid, "transfer", now, src=tr.src,
                             dst=tr.dst, pages=tr.pages,
                             latency_s=tr.ready_at - tr.issued_at)
        out.status = "running"
        touched[rid] = out

    def _observe_collapse(self, now: float):
        """One fleet-rung hysteresis tick per cluster round."""
        move = self._collapse_guard.observe(self._round_disagg_pressure)
        self._round_disagg_pressure = False
        if move == "collapse":
            self.disagg_counters["collapses"] += 1
            self.flight.record("disagg_collapse", now)
            if self.tracer is not None:
                self.tracer.event("disagg_collapse", now)
        elif move == "restore":
            self.disagg_counters["collapse_restores"] += 1
            self.flight.record("disagg_restore", now)
            if self.tracer is not None:
                self.tracer.event("disagg_restore", now)

    # ------------------------------------------------------------------
    # absorption / observability
    # ------------------------------------------------------------------
    def _absorb(self, rep: _Replica, out, touched: dict):
        """Fold one replica-level output into the cluster view.

        Duplicate-finalize dedup: only the request's CURRENT assignment
        may update it, and a terminal cluster output never regresses —
        a stale replica's late finalization (or a drained replica's
        leftover record) is ignored by construction."""
        rid = out.request_id
        meta = self._meta.get(rid)
        if meta is None or meta["replica"] != rep.rid:
            return
        cout = self._outputs[rid]
        if cout.finished:
            return
        cout.token_ids = list(out.token_ids)
        cout.status = out.status
        cout.finish_reason = out.finish_reason
        # lifetime preemption count: what crashed/drained former
        # replicas charged (folded into preempt_base at requeue) plus
        # the current assignment's own count
        cout.num_preemptions = meta["preempt_base"] + out.num_preemptions
        if cout.finished:
            self._unfinished.pop(rid, None)
            if self.fabric is not None:
                self.fabric.forget(rid)       # drop streaming credit
        touched[rid] = cout

    def metrics_snapshot(self) -> dict:
        """Fleet view: cluster counters, per-replica state/health/
        lifetime counters (crash-surviving), and time-in-state — the
        numbers the cluster report and the proxy-bench probe consume."""
        now = self._now()
        agg_state: dict[str, float] = {}
        reps = []
        for rep in self.replicas:
            st = dict(rep.state_time)
            st[rep.state.value] = st.get(rep.state.value, 0.0) \
                + (now - rep.state_since)
            for k, v in st.items():
                agg_state[k] = agg_state.get(k, 0.0) + v
            entry = {
                "replica": rep.rid,
                "state": rep.state.value,
                "state_time_s": st,
                "steps": rep.steps,
                "generation": rep.generation,
                "decommissioned": rep.decommissioned,
                "slow_multiplier": rep.slow_multiplier,
                "degradation_level": rep.ladder.level
                if rep.ladder is not None else 0,
                "health": dict(rep.health),
                # staleness signal (never silently current): how old
                # the health read is, and whether it predates the
                # replica's current body — a DOWN/RECOVERING replica's
                # last-known health is a post-mortem, not a reading
                "health_age_s": now - rep.health_at,
                "health_stale": rep.engine is None
                or rep.state in (ReplicaState.DOWN,
                                 ReplicaState.RECOVERING),
                "counters": {k: rep.counter(k)
                             for k in _CARRIED_COUNTERS},
            }
            if self._roles is not None:
                entry["role"] = rep.role
            reps.append(entry)
        out = dict(self.counters)
        out.update({
            "num_replicas": self.num_replicas,
            "provisioned_replicas": self.provisioned_replicas(),
            "retry_budget": self.retry_budget,
            "parked": len(self._parked),
            "time_in_state_s": agg_state,
            "replicas": reps,
        })
        if self._roles is not None:
            # disagg view: per-pool queue depths (routing pressure the
            # colocated gauges cannot show), the fabric's lifetime
            # counters, and the fleet rung's state — keyed off roles
            # mode so a colocated snapshot stays byte-identical
            def _pool_depth(role):
                return sum(r.health["queue_depth"] + r.health["running"]
                           for r in self.replicas
                           if r.role == role and r.engine is not None)
            out["disagg"] = {
                "collapsed": self.collapsed,
                "counters": dict(self.disagg_counters),
                "fabric": dict(self.fabric.counters),
                "transfers_in_flight": self.fabric.in_flight,
                "pending_injections": len(self._pending_injections),
                "prefill_queue_depth": _pool_depth("prefill"),
                "decode_queue_depth": _pool_depth("decode"),
                "fleet_prefix": dict(self.fleet_prefix.counters)
                if self.fleet_prefix is not None else None,
            }
        return out

    def next_transfer_t(self):
        """Virtual time of the earliest in-flight transfer landing
        (None when the fabric is idle or absent) — the driver's
        idle-jump bound alongside :meth:`next_fault_t`: a cluster
        waiting only on the wire must wake when the wire delivers."""
        if self.fabric is None or not self.fabric._inflight:
            return None
        return min(t.ready_at for t in self.fabric._inflight)


__all__ = ["ACTIVE_STATES", "ADMITTABLE_STATES", "ClusterEngine",
           "DegradationLadder", "FleetDegradation", "ReplicaState"]
