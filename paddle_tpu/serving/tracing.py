"""Request-lifecycle tracing + the always-on flight recorder.

Two observability surfaces with opposite cost/coverage trade-offs:

- :class:`RequestTracer` — an OPT-IN per-request span recorder: every
  request accumulates typed spans (enqueue, admission, each prefill
  chunk, decode/burst steps, speculative rounds and rollbacks,
  preemptions, deadline aborts, cross-replica retry hops) stamped on
  the engine's ``now_fn``. Under the loadgen virtual clock a seeded run
  therefore exports a BYTE-IDENTICAL trace (``export_json`` mirrors
  loadgen/report.py's fixed-precision sorted-key discipline), so "where
  did this request's p99 go" is an attributable, regression-testable
  question instead of a print-debugging session. Spans are host-side
  appends of plain tuples: tracing adds ZERO jitted dispatches and zero
  device syncs (tests/test_tracing.py gates the ragged trace-count and
  the host-dispatch-per-token ratio with tracing enabled). When the
  native profiler is recording, each span also lands as an instant on
  the host timeline next to op spans, and ``export_chrome_trace`` can
  merge both into one chrome://tracing JSON.

- :class:`FlightRecorder` — an ALWAYS-ON bounded ring buffer of engine/
  fleet events (one O(1) append per step plus notable events: preempt,
  shed, abort, degradation rung moves, faults, crashes). Memory is
  capped at ``capacity`` entries forever — a week-long serving run and
  a 200-step soak hold the same bytes. When something detonates — an
  ``InvariantViolation`` out of the pool audit, a nonfinite-logits
  abort, a replica crash — the recorder ``dump()``\\ s the last N events
  as a structured post-mortem attached to the failure (the exception's
  ``flight_dump``, the engine's ``flight.last_dump``), so the steps
  LEADING INTO the failure are part of the artifact, not lost.

Span timestamps come exclusively from the caller's ``now_fn`` clock:
nothing here reads wall-clock time, which is what makes the export
reproducible under loadgen and comparable across replicas (the cluster
stamps every replica's spans on the one fleet clock).
"""
from __future__ import annotations

import json
from collections import deque

from ..core import native as _nv

#: span kinds a request can accumulate, in the lifecycle's rough order.
#: ``detail`` payloads are small dicts of ints/floats/strings only —
#: everything in a trace must serialize deterministically.
SPAN_KINDS = (
    "enqueue",        # request entered an engine's queue (again, on retry)
    "park",           # cluster: no replica admittable, parked at the router
    "dispatch",       # cluster: routed to a replica
    "admission",      # scheduler moved it into the running set
    "prefill_chunk",  # one committed prompt chunk (q_len, cached after)
    "decode",         # one committed decode token (per-token path)
    "spec_round",     # one speculative round (drafted/accepted/rollback)
    "burst",          # one on-device burst (tokens committed at boundary)
    "preempt",        # preempted back to the queue (recompute mode)
    "retry_hop",      # cluster: requeued to another replica after a failure
    "shed",           # terminal: deadline/queue shed (reason in detail)
    "deadline_abort",  # terminal: mid-flight e2e SLO abort
    "nonfinite_abort",  # terminal: the in-graph isfinite guard fired
    "finish",         # terminal: finished / cancelled / aborted (reason)
    "kv_prefetch_stall",  # two-tier KV: a parked sequence's restore was
                          # not staged a full round ahead — the copy ran
                          # synchronously (counted, bounded; kv_tier.py)
    "transfer",       # disagg: KV pages in flight prefill -> decode pool
                      # (serving/fabric.py; detail carries pages/latency)
)

SCHEMA_VERSION = 1

#: float precision of the JSON export — same discipline as
#: loadgen/report.py: high enough that distinct virtual-clock stamps
#: never collide, fixed so byte-identity holds
_ROUND = 9


def _round_floats(obj):
    if isinstance(obj, float):
        return round(obj, _ROUND)
    if isinstance(obj, dict):
        return {k: _round_floats(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round_floats(v) for v in obj]
    return obj


class RequestTracer:
    """Deterministic per-request span recorder (opt-in; pass one as
    ``LLMEngine(tracer=...)`` or ``ClusterEngine(tracer=...)``).

    The tracer is deliberately dumb storage: callers stamp spans with
    the time base THEY serve under (the engine's ``now_fn``), and every
    ``detail`` value must already be a plain int/float/str/bool — the
    recorder never derives anything, so two runs that make the same
    calls export the same bytes.
    """

    __slots__ = ("_spans", "_events", "max_spans_per_request", "dropped")

    def __init__(self, *, max_spans_per_request=0):
        #: request_id -> [(t, kind, detail|None)] in record order
        self._spans: dict[str, list] = {}
        #: engine/fleet-scope events: [(t, kind, detail|None)]
        self._events: list = []
        #: optional per-request span cap (0 = unbounded): a runaway
        #: request drops its TAIL spans (counted in ``dropped``) instead
        #: of growing without bound
        self.max_spans_per_request = int(max_spans_per_request)
        self.dropped = 0

    # ---- recording ----
    def span(self, request_id, kind, t, **detail):
        """Append one span to ``request_id``'s trace at time ``t``."""
        lst = self._spans.get(request_id)
        if lst is None:
            lst = self._spans[request_id] = []
        if self.max_spans_per_request and \
                len(lst) >= self.max_spans_per_request:
            self.dropped += 1
            return
        lst.append((float(t), kind, detail or None))
        if _nv.prof_enabled():
            # live profiler timeline: the span lands as an instant next
            # to op spans (category 3 = the serving-gauge tier)
            _nv.prof_instant(f"trace.{kind}:{request_id}", 3)

    def event(self, kind, t, **detail):
        """Engine/fleet-scope event (degradation rung move, fault,
        crash, drain) — not attributed to one request."""
        self._events.append((float(t), kind, detail or None))
        if _nv.prof_enabled():
            _nv.prof_instant(f"trace.{kind}", 3)

    # ---- reading ----
    def spans(self, request_id) -> list:
        """[(t, kind, detail)] for one request ([] if never seen)."""
        return list(self._spans.get(request_id, ()))

    def events(self) -> list:
        return list(self._events)

    def request_ids(self) -> list:
        return list(self._spans)

    @property
    def span_count(self) -> int:
        return sum(len(v) for v in self._spans.values()) \
            + len(self._events)

    def clear(self):
        self._spans.clear()
        self._events.clear()
        self.dropped = 0

    # ---- export ----
    def export(self) -> dict:
        """Plain-dict structured trace: schema version, per-request span
        lists, fleet-scope events. Everything derives from ``now_fn``
        stamps and deterministic counters — serialize with
        :meth:`export_json` for the byte-identity gate."""
        return {
            "schema_version": SCHEMA_VERSION,
            "requests": {
                rid: [{"t": t, "kind": kind,
                       **({"detail": detail} if detail else {})}
                      for t, kind, detail in spans]
                for rid, spans in self._spans.items()
            },
            "events": [{"t": t, "kind": kind,
                        **({"detail": detail} if detail else {})}
                       for t, kind, detail in self._events],
            "dropped_spans": self.dropped,
        }

    def export_json(self) -> str:
        """Stable serialization (sorted keys, fixed float precision) —
        the determinism gate compares these bytes."""
        return json.dumps(_round_floats(self.export()), sort_keys=True,
                          indent=1)

    def export_chrome_trace(self, path=None, *, include_profiler=True,
                            time_scale_us=1e6, telemetry=None) -> dict:
        """chrome://tracing JSON of the trace: one tid per request, one
        instant event per span (virtual seconds scaled to microseconds
        by ``time_scale_us``), fleet events on tid 0 — and, when the
        native profiler has events and ``include_profiler`` is on, the
        host op spans merged in under a second pid so request lifecycle
        and op timeline sit in ONE viewer. ``telemetry`` (a
        :class:`~paddle_tpu.telemetry.Scraper`) adds a counter lane
        under pid 3: every fleet series sample as a chrome counter
        event, so queue depth / KV pressure / alert-feeding signals
        plot directly under the request spans. Returns the trace dict;
        writes it to ``path`` when given."""
        events = []
        tids = {}
        for rid in self._spans:
            tids[rid] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tids[rid], "args": {"name": rid}})
        for rid, spans in self._spans.items():
            for t, kind, detail in spans:
                events.append({"name": kind, "ph": "i", "s": "t",
                               "pid": 1, "tid": tids[rid],
                               "ts": t * time_scale_us,
                               "args": detail or {}})
        for t, kind, detail in self._events:
            events.append({"name": kind, "ph": "i", "s": "p", "pid": 1,
                           "tid": 0, "ts": t * time_scale_us,
                           "args": detail or {}})
        if include_profiler:
            for name, tid, start_ns, dur_ns, cat in _nv.prof_export():
                events.append({"name": name, "ph": "X", "pid": 2,
                               "tid": int(tid), "ts": start_ns / 1e3,
                               "dur": dur_ns / 1e3,
                               "args": {"category": int(cat)}})
        if telemetry is not None:
            # the fleet telemetry counter lane (pid 3): scraped series
            # as chrome counter tracks next to the request spans
            events.extend(
                telemetry.chrome_counter_events(time_scale_us))
        trace = {"traceEvents": events,
                 "displayTimeUnit": "ms",
                 "metadata": {"source": "paddle_tpu.serving.tracing",
                              "schema_version": SCHEMA_VERSION}}
        if path is not None:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace


#: span kinds that commit generated tokens (detail carries new_tokens)
_TOKEN_KINDS = ("decode", "burst", "spec_round", "prefill_chunk")


def request_breakdown(spans) -> dict | None:
    """Fold one request's span list into a queue/prefill/decode/stall
    latency attribution (seconds, same time base as the spans):

    - ``queue_s``    — first enqueue -> first admission;
    - ``prefill_s``  — first admission -> last committed prompt chunk
      (0 for a full prefix-cache hit admitted caught-up);
    - ``decode_s``   — first generated token -> finalization, minus the
      time the request's KV was in flight on the fabric;
    - ``transfer_s`` — disaggregated serving only: modeled fabric time
      shipping the request's KV pages prefill -> decode pool (sum of
      ``transfer`` span latencies; 0.0 when no handoff happened);
    - ``stall_s``    — everything else inside e2e: preemption requeues,
      retry backoff, re-prefill after a crash — the time the request
      was alive but not progressing its FIRST pass;
    - ``e2e_s``      — first enqueue -> terminal span.

    Returns None until the request has a terminal span.
    """
    t_enqueue = t_admit = t_first_tok = t_done = None
    t_prefill_end = None
    transfer = 0.0
    for t, kind, detail in spans:
        if kind == "enqueue" and t_enqueue is None:
            t_enqueue = t
        elif kind == "admission" and t_admit is None:
            t_admit = t
            t_prefill_end = t
        elif kind == "prefill_chunk" and t_first_tok is None:
            t_prefill_end = t
        elif kind == "transfer" and detail:
            transfer += detail.get("latency_s", 0.0)
        if t_first_tok is None and kind in _TOKEN_KINDS and detail \
                and detail.get("new_tokens", 0) > 0:
            t_first_tok = t
        if kind in ("finish", "shed", "deadline_abort",
                    "nonfinite_abort"):
            t_done = t
    if t_enqueue is None or t_done is None:
        return None
    e2e = t_done - t_enqueue
    queue = (t_admit - t_enqueue) if t_admit is not None else e2e
    prefill = (t_prefill_end - t_admit) if t_admit is not None else 0.0
    # fabric time lives inside the first-token -> done window (the
    # handoff fires after the first sampled token); carve it out of
    # decode so a slow fabric reads as transfer, not decode
    decode = (t_done - t_first_tok - transfer) \
        if t_first_tok is not None else 0.0
    decode = max(decode, 0.0)
    stall = max(e2e - queue - prefill - decode - transfer, 0.0)
    return {"queue_s": queue, "prefill_s": prefill, "decode_s": decode,
            "transfer_s": transfer, "stall_s": stall, "e2e_s": e2e}


def latency_breakdown(tracer: RequestTracer) -> dict:
    """Aggregate span-derived latency attribution over every request
    with a terminal span: per-component count/mean/p50/p90/p99 — the
    loadgen report's answer to "queue, prefill, decode, or stall: where
    did the p99 go?" (reports attach it under ``latency_breakdown``
    when built with ``tracer=``)."""
    from ..serving.metrics import percentile_of
    per_request = {}
    for rid in tracer.request_ids():
        b = request_breakdown(tracer.spans(rid))
        if b is not None:
            per_request[rid] = b
    out = {"requests": len(per_request)}
    for comp in ("queue_s", "prefill_s", "decode_s", "transfer_s",
                 "stall_s", "e2e_s"):
        vals = [b[comp] for b in per_request.values()]
        out[comp] = {
            "mean": sum(vals) / len(vals) if vals else None,
            "p50": percentile_of(vals, 50),
            "p90": percentile_of(vals, 90),
            "p99": percentile_of(vals, 99),
        }
    return out


class FlightRecorder:
    """Always-on bounded ring buffer of engine/fleet events.

    O(1) memory (a ``deque(maxlen=capacity)`` of small tuples) and O(1)
    per record — cheap enough to leave on in production serving loops.
    ``dump()`` snapshots the ring as a structured post-mortem; the last
    ``max_dumps`` dumps are retained so a cascade (crash -> invariant
    violation during requeue) keeps every stage's context.
    """

    __slots__ = ("capacity", "_ring", "dumps", "max_dumps", "_dump_cb")

    def __init__(self, capacity=256, *, max_dumps=8, on_dump=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        #: retained post-mortems, oldest first, capped at max_dumps
        self.dumps: list = []
        self.max_dumps = int(max_dumps)
        self._dump_cb = on_dump

    def record(self, kind, t, **fields):
        """Append one event; the ring silently drops the oldest entry
        beyond ``capacity`` — recording never allocates beyond it."""
        self._ring.append((float(t), kind, fields or None))

    def __len__(self):
        return len(self._ring)

    def events(self) -> list:
        """[(t, kind, fields)] oldest -> newest (a copy)."""
        return list(self._ring)

    def dump(self, reason, *, t=None, **detail) -> dict:
        """Snapshot the last-N events as a post-mortem dict:
        ``{reason, t, detail, events}``. Retained in ``dumps`` (bounded)
        and handed to the ``on_dump`` callback when one was given —
        the auto-dump hook for InvariantViolation / nonfinite aborts /
        replica crashes."""
        d = {
            "reason": reason,
            "t": t,
            "detail": detail or None,
            "events": [{"t": et, "kind": kind,
                        **({"fields": f} if f else {})}
                       for et, kind, f in self._ring],
        }
        self.dumps.append(d)
        if len(self.dumps) > self.max_dumps:
            del self.dumps[:len(self.dumps) - self.max_dumps]
        if self._dump_cb is not None:
            try:
                self._dump_cb(d)
            except Exception:
                pass   # a broken sink must never mask the real failure
        return d

    @property
    def last_dump(self) -> dict | None:
        return self.dumps[-1] if self.dumps else None


__all__ = ["SCHEMA_VERSION", "SPAN_KINDS", "FlightRecorder",
           "RequestTracer", "latency_breakdown", "request_breakdown"]
