"""LLMEngine — continuous-batching serving over the paged Pallas kernel.

Turns the repo's existing pieces (models/generation.py prefill math,
kernels/paged_attention.py decode kernel, the PagedKVPool allocator, the
bucketed Scheduler) into a request-lifecycle engine:

    engine = LLMEngine(model, max_len=256, page_size=16)
    rid = engine.add_request([1, 2, 3], max_new_tokens=8)
    while engine.has_unfinished():
        for out in engine.step():       # incremental token streaming
            ...
    tokens = engine.outputs()[rid].token_ids

Compilation contract (the TPU-shaped core of the design): the decode step
is one jitted function whose input shapes are always a (batch_bucket,
pages_bucket) pair from the scheduler's closed bucket set, so XLA compiles
at most ``len(batch_buckets) * len(pages_buckets)`` decode executables no
matter what request mix arrives (gated by
tests/test_serving_compile_gate.py). Prefill is likewise bucketed over
padded prompt lengths. Everything request-specific — block tables, true
lengths, sampling temperature — is data, not shape.

Greedy outputs are token-identical to sequential ``Generator.generate``:
prefill reuses ``generation._block`` verbatim, decode mirrors its math
over the shared pool, and preemption requeues in recompute mode (prefill
over prompt+generated reproduces the same greedy continuation).
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from ..models.generation import (_block, _logits, _rms_norm, _rope, _wmat,
                                 extract_params)
from ..kernels.paged_attention import paged_attention
from .kv_cache import NULL_PAGE, PagedKVPool
from .metrics import ServingMetrics
from .scheduler import (Scheduler, SchedulerConfig, Sequence, SequenceStatus,
                        bucket_for)


@dataclass
class Request:
    """What a client submits."""
    prompt_token_ids: list
    max_new_tokens: int = 16
    temperature: float = 0.0
    eos_token_id: int | None = None
    #: relative SLO in seconds: if the request is still *waiting* this long
    #: after submission, the scheduler sheds it instead of serving it late
    deadline_s: float | None = None
    request_id: str | None = None


@dataclass
class RequestOutput:
    """Live view of one request; ``token_ids`` grows as tokens stream."""
    request_id: str
    prompt_token_ids: list
    token_ids: list = field(default_factory=list)
    status: str = "waiting"
    finish_reason: str | None = None
    num_preemptions: int = 0

    @property
    def finished(self) -> bool:
        return self.status in ("finished", "shed", "cancelled", "aborted")


def _sample_rows(logits, key, temps):
    """Per-row sampling: temp<=0 rows take argmax (greedy, the parity
    path), temp>0 rows sample categorically at their own temperature."""
    greedy = jnp.argmax(logits, -1)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    sampled = jax.random.categorical(
        key, logits.astype(jnp.float32) / safe_t[:, None], -1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def _quantized_append(Pp, Ps, tok, page_ids, off, page_size):
    """Append one token per row into an int8 page with per-(head, page)
    scales. The page's scale is the running amax/127 of everything in it:
    when the new token raises it, the page's existing values are
    requantized in place (dequant -> round at the new scale), so earlier
    tokens stay within one rounding step of their fp values.

    Pp: [Hkv, num_pages, ps, d] int8; Ps: [Hkv, num_pages] f32;
    tok: [Hkv, B, d] fp; page_ids/off: [B]. Returns (Pp, Ps).
    """
    old_s = Ps[:, page_ids]                              # [Hkv, B]
    amax = jnp.max(jnp.abs(tok), axis=-1)                # [Hkv, B]
    new_s = jnp.maximum(old_s, jnp.maximum(amax, 1e-8) / 127.0)
    ratio = jnp.where(new_s > 0, old_s / new_s, 0.0)
    page_q = jnp.clip(jnp.round(
        Pp[:, page_ids].astype(jnp.float32) * ratio[:, :, None, None]),
        -127, 127)                                       # [Hkv, B, ps, d]
    tok_q = jnp.clip(jnp.round(tok / new_s[:, :, None]), -127, 127)
    sel = (jnp.arange(page_size)[None, None, :, None]
           == off[None, :, None, None])
    page_new = jnp.where(sel, tok_q[:, :, None, :], page_q) \
        .astype(jnp.int8)
    return Pp.at[:, page_ids].set(page_new), \
        Ps.at[:, page_ids].set(new_s)


def _decode_block(lyr, h, pos, cfg, Kp, Vp, tbls, lens, *, page_size,
                  interpret, Ks=None, Vs=None):
    """One decoder layer of the batched single-token decode over the
    SHARED paged pool (mirrors generation._block's decode math, but with
    real block tables instead of the Generator's identity mapping).

    h: [B, 1, hidden]; pos/lens: [B] cached length per row (write slot);
    Kp/Vp: [Hkv, num_pages, ps, d]; tbls: [B, pages_bucket].
    Padded rows carry all-NULL tables, so their writes and reads land on
    the null page and never touch live data.

    int8 pools pass Ks/Vs [Hkv, num_pages]: the token is quantized on
    append (per-page running scale, _quantized_append) and the Pallas
    kernel dequantizes at the gather. Returns (h, (Kp, Vp), (Ks, Vs));
    the scale pair is None for fp pools.
    """
    H, Hkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    b = h.shape[0]
    x = _rms_norm(h, lyr["ln1"], cfg.rms_norm_eps)
    q = _wmat(x, lyr["q"]).reshape(b, 1, H, d)
    k = _wmat(x, lyr["k"]).reshape(b, 1, Hkv, d)
    v = _wmat(x, lyr["v"]).reshape(b, 1, Hkv, d)
    q = _rope(q, pos[:, None], cfg.rope_theta, d)
    k = _rope(k, pos[:, None], cfg.rope_theta, d)

    # scatter the new token's K/V into each row's current page
    npages = Kp.shape[1]
    rows = jnp.arange(b)
    kt = jnp.transpose(k[:, 0], (1, 0, 2))          # [Hkv, B, d]
    vt = jnp.transpose(v[:, 0], (1, 0, 2))
    if Ks is not None:
        page_ids = tbls[rows, lens // page_size]
        off = lens % page_size
        Kp, Ks = _quantized_append(Kp, Ks, kt, page_ids, off, page_size)
        Vp, Vs = _quantized_append(Vp, Vs, vt, page_ids, off, page_size)
    else:
        slot = tbls[rows, lens // page_size] * page_size + lens % page_size
        Kp = Kp.reshape(Hkv, npages * page_size, d).at[:, slot].set(kt) \
               .reshape(Hkv, npages, page_size, d)
        Vp = Vp.reshape(Hkv, npages * page_size, d).at[:, slot].set(vt) \
               .reshape(Hkv, npages, page_size, d)

    o = paged_attention(q[:, 0], Kp, Vp, tbls, lens + 1,
                        interpret=interpret, k_scales=Ks,
                        v_scales=Vs)                # [B, H, d]
    h = h + _wmat(o.reshape(b, 1, H * d), lyr["o"])
    x = _rms_norm(h, lyr["ln2"], cfg.rms_norm_eps)
    h = h + _wmat(jax.nn.silu(_wmat(x, lyr["gate"])) * _wmat(x, lyr["up"]),
                  lyr["down"])
    return h, (Kp, Vp), (None if Ks is None else (Ks, Vs))


class LLMEngine:
    """Continuous-batching serving engine over a paged KV pool."""

    def __init__(self, model, *, max_len=256, page_size=16, num_pages=None,
                 batch_buckets=(1, 2, 4, 8), pages_buckets=None,
                 prefill_buckets=None, max_prefills_per_step=4,
                 high_watermark=0.90, low_watermark=0.50, seed=0,
                 stream_cb=None, now_fn=time.monotonic, interpret=None,
                 quantized_mode=None, kv_cache_dtype=None):
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of page_size "
                f"{page_size}")
        self.cfg = cfg = model.config
        self.params = extract_params(model)
        # low-bit serving weights: the jitted prefill/decode trace over a
        # quantized pytree; projections run the fused dequant-matmul
        self.quantized_mode = quantized_mode
        if quantized_mode is not None:
            from ..quantization.low_bit import quantize_params
            self.params = quantize_params(self.params, quantized_mode)
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages_per_seq = max_len // page_size
        if num_pages is None:
            # default: every batch slot can hold a max_len sequence, so
            # preemption never fires unless the operator shrinks the pool
            num_pages = max(batch_buckets) * self.max_pages_per_seq + 1
        if kv_cache_dtype in ("int8", jnp.int8, jnp.dtype(jnp.int8)):
            dtype = jnp.int8          # int8 pool: ~2x sequences per byte
        elif kv_cache_dtype is not None:
            dtype = jnp.dtype(kv_cache_dtype)
        else:
            dtype = self.params["embed"].dtype
        self.pool = PagedKVPool(
            cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim,
            num_pages=num_pages, page_size=page_size, dtype=dtype,
            high_watermark=high_watermark, low_watermark=low_watermark)
        self.metrics = ServingMetrics(now_fn=now_fn)
        self.scheduler = Scheduler(
            self.pool,
            SchedulerConfig(batch_buckets=batch_buckets,
                            pages_buckets=pages_buckets,
                            max_prefills_per_step=max_prefills_per_step,
                            now_fn=now_fn),
            self.max_pages_per_seq, metrics=self.metrics)
        self.prefill_buckets = tuple(sorted(set(
            prefill_buckets or self._default_prefill_buckets())))
        if max(self.prefill_buckets) < max_len:
            raise ValueError("largest prefill bucket must reach max_len")
        for s in self.prefill_buckets:
            if s % page_size != 0:
                raise ValueError(f"prefill bucket {s} not a multiple of "
                                 f"page_size {page_size}")
        if interpret is None:
            from ..kernels import _on_tpu
            interpret = not _on_tpu()
        self._interpret = interpret
        self._now = now_fn
        self._stream_cb = stream_cb
        self._key = jax.random.key(seed)
        self._ids = itertools.count()
        self._seqs: dict[str, Sequence] = {}
        self._outputs: dict[str, RequestOutput] = {}
        self._prefill_shapes: set[int] = set()
        self._decode_shapes: set[tuple[int, int]] = set()
        self._build_steps()

    def _default_prefill_buckets(self):
        # the pages bucket ladder scaled to token units: one bucket
        # policy shared with the scheduler, two units
        return [p * self.page_size for p in
                SchedulerConfig.default_pages_buckets(
                    self.max_pages_per_seq)]

    # ------------------------------------------------------------------
    # jitted steps (fixed shapes per bucket)
    # ------------------------------------------------------------------
    def _build_steps(self):
        cfg = self.cfg
        ps = self.page_size
        interpret = self._interpret
        quant_pool = self.pool.quantized

        def prefill(params, kv, kv_scales, ids, length, tbl, temp, key):
            # ids [1, S] padded; tbl [S // ps] page ids (NULL-padded).
            b, s = ids.shape
            pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
            h = params["embed"][ids]
            new_kv, new_scales = [], []
            for i, (lyr, (Kp, Vp)) in enumerate(zip(params["layers"], kv)):
                h, (k, v) = _block(lyr, h, pos, cfg)
                # [1, S, Hkv, d] -> [Hkv, S/ps, ps, d] -> scatter to pool
                hkv, d = cfg.num_key_value_heads, cfg.head_dim
                kt = jnp.transpose(
                    k[0].reshape(s // ps, ps, hkv, d), (2, 0, 1, 3))
                vt = jnp.transpose(
                    v[0].reshape(s // ps, ps, hkv, d), (2, 0, 1, 3))
                if quant_pool:
                    # exact per-(head, page) scales from the prompt's own
                    # amax. Padded positions are ZEROED first: the pad
                    # token id 0 has a real embedding, so its K/V would
                    # otherwise inflate the last partial page's scale and
                    # coarsen the real tokens' quantization (attention
                    # never reads past `length`, so zeroing loses nothing)
                    Ks, Vs = kv_scales[i]
                    valid = (jnp.arange(s) < length).reshape(
                        s // ps, ps)[None, :, :, None]

                    def _q(t):
                        t = jnp.where(valid, t, 0.0)
                        s_ = jnp.maximum(jnp.max(jnp.abs(t), axis=(2, 3)),
                                         1e-8) / 127.0
                        q_ = jnp.clip(jnp.round(t / s_[:, :, None, None]),
                                      -127, 127).astype(jnp.int8)
                        return q_, s_

                    kq, k_s = _q(kt)
                    vq, v_s = _q(vt)
                    new_kv.append((Kp.at[:, tbl].set(kq),
                                   Vp.at[:, tbl].set(vq)))
                    new_scales.append((Ks.at[:, tbl].set(k_s),
                                       Vs.at[:, tbl].set(v_s)))
                else:
                    new_kv.append((Kp.at[:, tbl].set(kt),
                                   Vp.at[:, tbl].set(vt)))
            h = _rms_norm(h, params["norm"], cfg.rms_norm_eps)
            last = jax.lax.dynamic_index_in_dim(h, length - 1, axis=1,
                                                keepdims=False)
            logits = _logits(params, last, cfg)             # [1, V]
            tok = _sample_rows(logits, key, temp[None])[0]
            return tok, new_kv, new_scales if quant_pool else None

        def decode(params, kv, kv_scales, tokens, tbls, lens, temps, key):
            # tokens/lens/temps [B]; tbls [B, P]. lens = cached length per
            # row = the write slot of this token; attention covers lens+1.
            h = params["embed"][tokens[:, None]]
            new_kv, new_scales = [], []
            for i, (lyr, (Kp, Vp)) in enumerate(zip(params["layers"], kv)):
                Ks, Vs = kv_scales[i] if quant_pool else (None, None)
                h, pair, scales = _decode_block(
                    lyr, h, lens, cfg, Kp, Vp, tbls, lens, page_size=ps,
                    interpret=interpret, Ks=Ks, Vs=Vs)
                new_kv.append(pair)
                new_scales.append(scales)
            h = _rms_norm(h, params["norm"], cfg.rms_norm_eps)
            logits = _logits(params, h[:, 0], cfg)          # [B, V]
            return (_sample_rows(logits, key, temps), new_kv,
                    new_scales if quant_pool else None)

        # donate the pool buffers (args 1-2: pages + scales) so decode
        # updates in place on TPU; CPU/PJRT-cpu ignores donation with a
        # warning, so skip there
        from ..kernels import _on_tpu
        donate = (1, 2) if _on_tpu() else ()
        self._prefill_jit = jax.jit(prefill, donate_argnums=donate)
        self._decode_jit = jax.jit(decode, donate_argnums=donate)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def add_request(self, prompt_token_ids, *, max_new_tokens=16,
                    temperature=0.0, eos_token_id=None, deadline_s=None,
                    request_id=None):
        """Queue a request; returns its id. Accepts a Request too."""
        if isinstance(prompt_token_ids, Request):
            r = prompt_token_ids
            return self.add_request(
                r.prompt_token_ids, max_new_tokens=r.max_new_tokens,
                temperature=r.temperature, eos_token_id=r.eos_token_id,
                deadline_s=r.deadline_s, request_id=r.request_id)
        prompt = [int(t) for t in np.asarray(prompt_token_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_len {self.max_len}")
        rid = request_id or f"req-{next(self._ids)}"
        if rid in self._seqs:
            raise KeyError(f"duplicate request_id {rid!r}")
        now = self._now()
        seq = Sequence(
            seq_id=rid, prompt_ids=prompt, max_new_tokens=max_new_tokens,
            arrival=now,
            deadline=None if deadline_s is None else now + deadline_s,
            temperature=temperature, eos_token_id=eos_token_id)
        self.scheduler.add(seq)
        self._seqs[rid] = seq
        self._outputs[rid] = RequestOutput(rid, prompt)
        self.metrics.requests_added.inc()
        return rid

    def cancel(self, request_id) -> bool:
        """Gracefully cancel: frees pages if running, keeps the tokens
        streamed so far in the output. Returns False if already done."""
        seq = self.scheduler.remove(request_id)
        if seq is None:
            return False
        self._finalize(seq, "cancelled")
        self.metrics.cancelled_requests.inc()
        return True

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    def outputs(self) -> dict:
        return dict(self._outputs)

    def release(self, request_id) -> "RequestOutput":
        """Drop a RESOLVED request's retained state (the client has
        consumed its output). A long-running server must call this (or
        use stream_cb and release on the finished event) — the engine
        retains finished outputs until released so polling clients can
        always fetch them."""
        out = self._outputs.get(request_id)
        if out is None:
            raise KeyError(f"unknown request {request_id!r}")
        if not out.finished:
            raise ValueError(
                f"request {request_id!r} is still {out.status}; "
                f"cancel() it before release()")
        del self._outputs[request_id]
        del self._seqs[request_id]
        return out

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["decode_cache_size"] = self.decode_cache_size()
        return snap

    def decode_cache_size(self):
        """Actual XLA compile count of the decode step (falls back to the
        bucket-signature count when the jit cache is not introspectable)."""
        try:
            return int(self._decode_jit._cache_size())
        except Exception:
            return len(self._decode_shapes)

    def step(self):
        """One scheduler round: shed -> admit+prefill -> decode batch.
        Returns the RequestOutputs touched this step (token streamed,
        finished, shed, or preempted)."""
        touched = {}
        for seq in self.scheduler.shed_expired():
            self._finalize(seq, "shed")
            touched[seq.seq_id] = self._outputs[seq.seq_id]
        for seq in self.scheduler.admit():
            tok = self._prefill_seq(seq)
            self._commit_token(seq, tok)
            touched[seq.seq_id] = self._outputs[seq.seq_id]
        plan = self.scheduler.prepare_decode()
        for t in self.scheduler.last_preempted:
            self._sync_output(t)           # surface fresh preemptions once
            touched[t.seq_id] = self._outputs[t.seq_id]
        if plan is not None:
            tokens = self._decode_plan(plan)
            for seq, tok in zip(plan.seqs, tokens):
                self._commit_token(seq, int(tok))
                touched[seq.seq_id] = self._outputs[seq.seq_id]
            self.metrics.decode_steps.inc()
        self.metrics.record_step(self.scheduler, self.pool)
        return list(touched.values())

    def run(self, max_steps=None):
        """Drive step() until every request resolves; returns outputs."""
        steps = 0
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps")
        return self.outputs()

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _prefill_seq(self, seq: Sequence) -> int:
        ids = seq.prompt_ids + seq.tokens      # recompute mode on requeue
        L = len(ids)
        S = bucket_for(L, self.prefill_buckets)
        if S not in self._prefill_shapes:
            self._prefill_shapes.add(S)
            self.metrics.prefill_compiles.inc()
        padded = np.zeros((1, S), np.int32)
        padded[0, :L] = ids
        tbl = np.asarray(
            self.pool.padded_block_table(seq.seq_id, S // self.page_size),
            np.int32)
        tok, new_kv, new_scales = self._prefill_jit(
            self.params, self.pool.kv, self.pool.kv_scales,
            jnp.asarray(padded), np.int32(L), jnp.asarray(tbl),
            np.float32(seq.temperature), self._next_key())
        self.pool.kv = new_kv
        if new_scales is not None:
            self.pool.kv_scales = new_scales
        self.metrics.prefills.inc()
        return int(tok)

    def _decode_plan(self, plan):
        B, P = plan.batch_bucket, plan.pages_bucket
        if (B, P) not in self._decode_shapes:
            self._decode_shapes.add((B, P))
            self.metrics.decode_compiles.inc()
        tokens = np.zeros((B,), np.int32)
        tbls = np.full((B, P), NULL_PAGE, np.int32)
        lens = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        for i, seq in enumerate(plan.seqs):
            tokens[i] = seq.tokens[-1]
            table = self.pool.padded_block_table(seq.seq_id, P)
            tbls[i] = table
            lens[i] = seq.total_len - 1        # cached length = write slot
            temps[i] = seq.temperature
        next_toks, new_kv, new_scales = self._decode_jit(
            self.params, self.pool.kv, self.pool.kv_scales,
            jnp.asarray(tokens), jnp.asarray(tbls), jnp.asarray(lens),
            jnp.asarray(temps), self._next_key())
        self.pool.kv = new_kv
        if new_scales is not None:
            self.pool.kv_scales = new_scales
        return np.asarray(next_toks)[:len(plan.seqs)]

    def _commit_token(self, seq: Sequence, tok: int):
        seq.tokens.append(int(tok))
        self.metrics.tokens_generated.inc()
        out = self._sync_output(seq)
        if seq.eos_token_id is not None and tok == seq.eos_token_id:
            self._finalize(seq, "finished", reason="eos")
        elif len(seq.tokens) >= seq.max_new_tokens:
            self._finalize(seq, "finished", reason="length")
        elif self._stream_cb is not None:
            self._stream_cb(seq.seq_id, int(tok), False)
        return out

    def _finalize(self, seq: Sequence, status: str, reason=None):
        self.scheduler.finish(seq, {
            "finished": SequenceStatus.FINISHED,
            "shed": SequenceStatus.SHED,
            "cancelled": SequenceStatus.CANCELLED,
            "aborted": SequenceStatus.ABORTED,
        }[status])
        out = self._sync_output(seq)
        out.finish_reason = reason or status
        if status == "finished":
            self.metrics.finished_requests.inc()
        if self._stream_cb is not None:
            last = seq.tokens[-1] if seq.tokens else None
            self._stream_cb(seq.seq_id, last, True)
        return out

    def _sync_output(self, seq: Sequence) -> RequestOutput:
        out = self._outputs[seq.seq_id]
        out.token_ids = list(seq.tokens)
        out.status = seq.status.value
        out.num_preemptions = seq.num_preemptions
        return out


__all__ = ["LLMEngine", "Request", "RequestOutput"]
