"""LLMEngine — continuous-batching serving over the ragged Pallas kernel.

Turns the repo's existing pieces (models/generation.py forward math,
kernels/paged_attention.py ragged kernel, the refcounted PagedKVPool, the
chunked-prefill Scheduler) into a request-lifecycle engine:

    engine = LLMEngine(model, max_len=256, page_size=16)
    rid = engine.add_request([1, 2, 3], max_new_tokens=8)
    while engine.has_unfinished():
        for out in engine.step():       # incremental token streaming
            ...
    tokens = engine.outputs()[rid].token_ids

Compilation contract (the TPU-shaped core of the design): EVERY engine
step — any mix of decode rows and prefill chunks, any batch composition,
any lengths — is one launch of ONE jitted ragged step whose input shapes
never change: ``step_token_budget`` packed query tokens over
``max_num_seqs`` row slots and ``max_pages_per_seq``-wide block tables.
XLA compiles exactly one step executable for the lifetime of the process
(gated by tests/test_serving_compile_gate.py) — down from the previous
``len(batch_buckets) * len(pages_buckets) + #prefill_buckets`` zoo.
Everything request-specific — block tables, (q_start, q_len, kv_len)
row metadata, sampling temperature — is data, not shape.

Prefix caching: after a prompt is fully committed, the engine registers
its page-aligned token-prefix chains in a hash map; a later request whose
prompt starts with a registered chain is admitted by FORKING the donor's
pages (``PagedKVPool.fork`` — refcount + 1, zero prefill compute, zero
page storage for the shared region). An identical prompt shares even the
partially-filled tail page; the first divergent append then triggers one
copy-on-write page duplication. int8 pools share full pages only: an
append can requantize a page in place (running-amax scale growth), which
must never perturb another reader's view.

Sampling: per-request knobs (temperature/top_k/top_p) travel as per-row
data through the one step, and every random draw comes from a
per-request ``fold_in(seed, generation position, tag)`` stream — a
request's sampled tokens are bit-identical across batch compositions,
chunking, preemption-recompute, and per-token vs burst execution.

Speculative decoding: ``LLMEngine(draft_model=..., spec_tokens=k)``
adds an int4 draft (serving/spec_decode.py) whose k proposals per
decode row are verified in ONE launch of the same ragged executable
(rows become q_len=k+1 prefill-shaped chunks); accepted tokens commit
normally, rejected tails roll the KV length back without freeing pages.

Greedy outputs are token-identical to sequential ``Generator.generate``:
the ragged step computes each token's K/V and logits independently of how
the work was chunked, so chunk boundaries, preemption-with-requeue
(recompute mode) and prefix forks all reproduce the same continuation —
with or without a draft model (rejection sampling degenerates to
argmax-equality on greedy rows).
"""
from __future__ import annotations

import itertools
import os
import time
import zlib
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from ..models.generation import (_logits, _rms_norm, _rope, _wmat,
                                 extract_params, request_keys, sample_rows)
from ..kernels.paged_attention import ragged_paged_attention
from .kv_cache import NULL_PAGE, PagedKVPool, PoolExhausted
from .metrics import ServingMetrics
from .scheduler import Scheduler, SchedulerConfig, Sequence, SequenceStatus
from .spec_decode import (FINAL_TAG, _ragged_fp_layer, _ragged_packing,
                          speculative_sample)


class PrefixStoreMismatch(ValueError):
    """A persisted prefix store cannot feed the live pool: the stored
    geometry/dtype disagrees with the engine's. This is an OPERATOR
    error (pointing a differently-configured engine at an old store),
    not corruption — so unlike a corrupt store (which cold-starts with
    a counter), it raises, carrying BOTH configs so the drift is
    diagnosable from the exception alone."""

    def __init__(self, live_config, stored_config):
        self.live_config = dict(live_config)
        self.stored_config = dict(stored_config)
        drift = {k for k in set(live_config) | set(stored_config)
                 if live_config.get(k) != stored_config.get(k)}
        super().__init__(
            f"prefix store does not match the live KV pool "
            f"(drifted: {sorted(drift)}): live={self.live_config} "
            f"stored={self.stored_config}")


class RequestRejected(ValueError):
    """Structured admission rejection: the request could never be served
    (prompt + max_new_tokens exceeds max_len or the pool's page limit).
    The engine records a finalized ``RequestOutput`` (status "aborted",
    ``finish_reason`` describing why) under ``request_id`` before
    raising, so the serving loop keeps running and polling clients see a
    terminal state instead of the whole engine dying mid-``step()``."""

    def __init__(self, request_id, reason, *, needed_pages=None,
                 limit=None, message=None):
        super().__init__(message or reason)
        self.request_id = request_id
        self.reason = reason
        self.needed_pages = needed_pages
        self.limit = limit


@dataclass
class Request:
    """What a client submits."""
    prompt_token_ids: list
    max_new_tokens: int = 16
    temperature: float = 0.0
    #: per-request sampling knobs: top-k (0/None = off), top-p nucleus
    #: (None/1.0 = off), and the request's own PRNG seed — a fixed
    #: (seed, prompt) reproduces the same sampled tokens bit for bit
    #: regardless of batch composition (None derives a stable seed from
    #: the request_id)
    top_k: int | None = None
    top_p: float | None = None
    seed: int | None = None
    eos_token_id: int | None = None
    #: relative SLO in seconds: if the request is still *waiting* this long
    #: after submission, the scheduler sheds it instead of serving it late
    deadline_s: float | None = None
    #: relative e2e SLO in seconds: if the request is still UNFINISHED
    #: this long after submission — running rows included — it is
    #: aborted at the next step boundary (reason "deadline_exceeded")
    #: instead of decoding tokens nobody will read
    abort_after_s: float | None = None
    request_id: str | None = None
    #: multi-tenant serving (paddle_tpu.tenancy): the submitting tenant
    #: (None = untenanted) and the LoRA adapter the request wears —
    #: None resolves to the tenant's default adapter (or the base
    #: model), 0 is explicitly the base model
    tenant_id: str | None = None
    adapter_id: object = None


@dataclass
class RequestOutput:
    """Live view of one request; ``token_ids`` grows as tokens stream."""
    request_id: str
    prompt_token_ids: list
    token_ids: list = field(default_factory=list)
    status: str = "waiting"
    finish_reason: str | None = None
    num_preemptions: int = 0
    tenant_id: str | None = None

    @property
    def finished(self) -> bool:
        return self.status in ("finished", "shed", "cancelled", "aborted")


def _quantized_append(Pp, Ps, tok, page_ids, off, page_size, live):
    """Append one token per row into an int8 page with per-(head, page)
    scales. The page's scale is the running amax/127 of everything in it:
    when the new token raises it, the page's existing values are
    requantized in place (dequant -> round at the new scale), so earlier
    tokens stay within one rounding step of their fp values.

    Pp: [Hkv, num_pages, ps, d] int8; Ps: [Hkv, num_pages] f32;
    tok: [Hkv, B, d] fp; page_ids/off/live: [B]. Dead rows (live=False)
    target the null page with an unchanged scale and write nothing.
    Returns (Pp, Ps).
    """
    old_s = Ps[:, page_ids]                              # [Hkv, B]
    amax = jnp.max(jnp.abs(tok), axis=-1)                # [Hkv, B]
    new_s = jnp.where(live[None, :],
                      jnp.maximum(old_s, jnp.maximum(amax, 1e-8) / 127.0),
                      old_s)
    ratio = jnp.where(new_s > 0, old_s / new_s, 0.0)
    page_q = jnp.clip(jnp.round(
        Pp[:, page_ids].astype(jnp.float32) * ratio[:, :, None, None]),
        -127, 127)                                       # [Hkv, B, ps, d]
    tok_q = jnp.clip(jnp.round(tok / jnp.maximum(new_s[:, :, None], 1e-30)),
                     -127, 127)
    sel = (jnp.arange(page_size)[None, None, :, None]
           == off[None, :, None, None]) & live[None, :, None, None]
    page_new = jnp.where(sel, tok_q[:, :, None, :], page_q) \
        .astype(jnp.int8)
    return Pp.at[:, page_ids].set(page_new), \
        Ps.at[:, page_ids].set(new_s)


def _segmented_quant_append(Pp, Ps, chunk, tbls, q_starts, q_lens, kv_lens,
                            page_size, max_pages, chunk_cap):
    """Segmented int8 chunk append: ONE running-amax requant per touched
    (head, page) instead of the old per-token chunk walk (chunk_cap
    sequential rounds of dequant->round, PR 6's named follow-up).

    Per touched page the final scale is ``max(old_scale, amax(new
    tokens in the page) / 127)`` — exactly what the sequential walk
    converges to — the page's existing content is requantized ONCE at
    that scale, and every new token is quantized directly at it (the
    walk round-tripped early tokens through each intermediate scale;
    quantizing at the final scale skips that double rounding, so values
    land within one rounding step of the walk and a single-token append
    is :func:`_quantized_append`'s math exactly). The loop runs over
    touched page SLOTS (``chunk_cap // page_size + 1`` worst case,
    traced-bounded to the live maximum — ONE iteration for
    decode-heavy launches) not chunk positions.

    Pp: [Hkv, num_pages, ps, d] int8; Ps: [Hkv, num_pages] f32;
    chunk: [Hkv, T, d] fp new tokens packed row-wise (the ragged step's
    query packing); tbls/q_starts/q_lens/kv_lens as in the ragged step.
    Rows own disjoint write pages (CoW guarantees it), dead rows target
    the null page and write nothing. Returns (Pp, Ps).
    """
    ps = page_size
    rows = jnp.arange(tbls.shape[0])
    start = jnp.maximum(kv_lens - q_lens, 0)               # [R]
    first_page = start // ps
    last_page = jnp.where(q_lens > 0, jnp.maximum(kv_lens - 1, 0) // ps,
                          first_page - 1)
    max_touched = -(-chunk_cap // ps) + 1
    bound = jnp.clip(jnp.max(last_page - first_page + 1), 0, max_touched)

    def body(j, carry):
        Pp, Ps = carry
        pidx = first_page + j                              # [R]
        pg_lo = pidx * ps
        w_lo = jnp.maximum(start, pg_lo)                   # write range
        w_hi = jnp.minimum(kv_lens, pg_lo + ps)            # ∩ this page
        live = (w_lo < w_hi) & (q_lens > 0)
        page = jnp.where(live,
                         tbls[rows, jnp.clip(pidx, 0, max_pages - 1)],
                         NULL_PAGE)
        slot_pos = pg_lo[:, None] + jnp.arange(ps)[None, :]   # [R, ps]
        tok_idx = jnp.clip(q_starts[:, None] + slot_pos - start[:, None],
                           0, chunk.shape[1] - 1)
        sel = (slot_pos >= w_lo[:, None]) & (slot_pos < w_hi[:, None]) \
            & live[:, None]                                # [R, ps]
        new = chunk[:, tok_idx]                            # [Hkv, R, ps, d]
        amax = jnp.max(jnp.where(sel[None, :, :, None], jnp.abs(new), 0.0),
                       axis=(2, 3))                        # [Hkv, R]
        old_s = Ps[:, page]
        new_s = jnp.where(live[None, :],
                          jnp.maximum(old_s,
                                      jnp.maximum(amax, 1e-8) / 127.0),
                          old_s)
        ratio = jnp.where(new_s > 0, old_s / new_s, 0.0)
        page_q = jnp.clip(jnp.round(
            Pp[:, page].astype(jnp.float32) * ratio[:, :, None, None]),
            -127, 127)
        tok_q = jnp.clip(jnp.round(
            new / jnp.maximum(new_s[:, :, None, None], 1e-30)), -127, 127)
        page_new = jnp.where(sel[None, :, :, None], tok_q, page_q) \
            .astype(jnp.int8)
        return (Pp.at[:, page].set(page_new), Ps.at[:, page].set(new_s))

    return jax.lax.fori_loop(0, bound, body, (Pp, Ps))


class LLMEngine:
    """Continuous-batching serving engine over a paged KV pool."""

    def __init__(self, model, *, max_len=256, page_size=16, num_pages=None,
                 max_num_seqs=None, chunk_size=None, q_block=8,
                 step_token_budget=None, batch_buckets=None,
                 pages_buckets=None, prefill_buckets=None,
                 max_prefills_per_step=4, prefix_caching=True,
                 prefix_cache_size=4096, pinned_prefix_pages=0,
                 high_watermark=0.90, low_watermark=0.50, seed=0,
                 stream_cb=None, now_fn=time.monotonic, interpret=None,
                 quantized_mode=None, kv_cache_dtype=None,
                 burst_tokens=None, draft_model=None, spec_tokens=None,
                 draft_quantized_mode="weight_only_int4",
                 draft_num_pages=None, mesh=None, tracer=None,
                 flight_recorder=None, flight_capacity=256,
                 engine_id=None, gauge_stale_after_s=None,
                 prefix_store=None, prefix_store_autosave=None,
                 host_kv_pages=0, kv_prefetch=True, kv_prefetch_depth=4,
                 kv_spill_seed=0, fleet_prefix_cache=None,
                 tenants=None, adapter_slots=0, adapter_rank=8,
                 adapter_store=None, adapter_store_autosave=None,
                 megakernel_scope=None, prefill_megakernel=None):
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of page_size "
                f"{page_size}")
        if burst_tokens is None:
            from ..core.flags import GLOBAL_FLAGS
            burst_tokens = int(GLOBAL_FLAGS.get("decode_burst_tokens"))
        if burst_tokens < 1:
            raise ValueError(f"burst_tokens must be >= 1, got "
                             f"{burst_tokens}")
        # speculative decoding: active iff a draft model is given; the
        # draft length comes from spec_tokens / FLAGS_spec_decode_tokens
        # (a draft model with neither set gets a default of 4)
        if spec_tokens is None:
            from ..core.flags import GLOBAL_FLAGS
            spec_tokens = int(GLOBAL_FLAGS.get("spec_decode_tokens"))
            if draft_model is not None and spec_tokens < 1:
                spec_tokens = 4
        if spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got {spec_tokens}")
        if draft_model is None:
            spec_tokens = 0
        if spec_tokens > 0 and burst_tokens > 1:
            raise ValueError(
                "speculative decoding and the on-device burst loop are "
                "mutually exclusive decode accelerations — set "
                "burst_tokens=1 (the default) when passing draft_model")
        # whole-model decode megakernel scope (ROADMAP item 4 / MPK):
        # 'layer' keeps today's unrolled per-layer launches; 'model'
        # moves the layer loop inside the traced program as a lax.scan
        # over stacked [L, ...] weights + KV pools — one launch per
        # token (and per burst). Token output is bitwise identical
        # between scopes; jit/hlo_forensics.launch_stats holds the
        # collapse (engine.launch_stats()).
        from ..models.generation import (resolve_megakernel_scope,
                                         resolve_prefill_megakernel)
        self.megakernel_scope = resolve_megakernel_scope(megakernel_scope)
        # ragged prefill launch shape (ROADMAP item 4's prefill-side
        # remainder): 'unfused' keeps the per-projection layer bodies;
        # 'fused' routes the whole ragged chain through
        # kernels/prefill_megakernel.fused_prefill_layer — fused
        # concat-dot projections over a step-hoisted rope/slot/block-row
        # prologue. Token output is bitwise identical between modes
        # (tests/test_prefill_megakernel.py).
        self.prefill_megakernel = resolve_prefill_megakernel(
            prefill_megakernel)
        # multi-tenant LoRA (paddle_tpu.tenancy): an adapter store with
        # no explicit slot count still needs a registry to reload into
        if adapter_store is not None and not adapter_slots:
            adapter_slots = 4
        if adapter_slots and burst_tokens > 1:
            raise ValueError(
                "batched LoRA adapters run inside the ragged step; the "
                "on-device burst loop (decode megakernel) has no adapter "
                "path — set burst_tokens=1 (the default) when passing "
                "adapter_slots/adapter_store")
        self.spec_tokens = spec_tokens
        #: runtime eligibility gate for speculative rounds — the
        #: degradation ladder's first rung flips it off under pressure
        #: (and back on when pressure clears). It never changes operand
        #: shapes: the one compiled executable keeps its K = spec_tokens
        #: layout, disabled rounds simply stop planning spec rows.
        self.spec_enabled = True
        #: on-device generation burst length: when > 1 and every running
        #: row is a caught-up decode row, the engine dispatches ONE
        #: jitted lax.while_loop of up to this many sample->append->gate
        #: iterations instead of one ragged step per token; the
        #: scheduler re-syncs (admission / preemption / CoW / prefix
        #: registration) at burst boundaries. 1 = the per-token path,
        #: bit-identical to the pre-burst engine.
        self.burst_tokens = burst_tokens
        self.cfg = cfg = model.config
        self.params = extract_params(model)
        # low-bit serving weights: the jitted ragged step traces over a
        # quantized pytree; projections run the fused dequant-matmul
        self.quantized_mode = quantized_mode
        if quantized_mode is not None:
            from ..quantization.low_bit import quantize_params
            self.params = quantize_params(self.params, quantized_mode)
        # tensor-parallel serving (distributed/gspmd.py): every
        # projection splits over the mesh's model axis (column/row
        # parallel; embed/lm_head on the vocab axis) and the paged KV
        # pool shards its kv-head axis the same way — the ONE jitted
        # ragged step picks the placements up by sharding inference, so
        # the trace-count==1 compile gate is untouched. Accepts a jax
        # Mesh with a 'model' axis, a ProcessMesh, or an int tp degree.
        self.mesh = None
        if mesh is not None:
            from ..distributed import gspmd as _gspmd
            import jax as _jax
            if isinstance(mesh, int):
                mesh = _gspmd.build_mesh(
                    _gspmd.ShardingConfig(data=1, model=mesh),
                    devices=_jax.devices()[:mesh])
            elif hasattr(mesh, "jax_mesh"):       # ProcessMesh
                mesh = mesh.jax_mesh
            if _gspmd.MODEL_AXIS not in mesh.shape:
                raise ValueError(
                    f"LLMEngine(mesh=...) needs a '{_gspmd.MODEL_AXIS}' "
                    f"mesh axis, got axes {tuple(mesh.shape)}")
            tp = mesh.shape[_gspmd.MODEL_AXIS]
            if cfg.num_key_value_heads % tp:
                raise ValueError(
                    f"LLMEngine(mesh=...): {cfg.num_key_value_heads} kv "
                    f"heads do not divide over the {tp}-way model axis "
                    f"(the KV pool shards per kv head)")
            self.mesh = mesh
            self.params = _gspmd.shard_serving_params(self.params, mesh)
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages_per_seq = max_len // page_size
        # legacy bucket knobs: max(batch_buckets) still sets the row-slot
        # count; pages_buckets/prefill_buckets are obsolete (the ragged
        # step has ONE shape) and accepted only for call-site compat
        del prefill_buckets
        if max_num_seqs is None:
            max_num_seqs = max(batch_buckets) if batch_buckets else 8
        if chunk_size is None:
            chunk_size = min(64, max_len)
        chunk_size = min(chunk_size, max_len)
        self.chunk_size = chunk_size
        if self.spec_tokens > 0:
            # a speculative round packs spec_tokens+1 query tokens into
            # EVERY row slot; the fixed-shape budget must hold that for
            # a full house of rows (spec_len never shrinks under
            # pressure — that would change which stream positions get
            # drafted and break per-request bit-reproducibility)
            need = max_num_seqs * (-(-(self.spec_tokens + 1) // q_block)
                                   * q_block)
            if step_token_budget is None:
                default = max_num_seqs * q_block + \
                    -(-chunk_size // q_block) * q_block
                step_token_budget = max(default, need)
            elif step_token_budget < need:
                raise ValueError(
                    f"step_token_budget {step_token_budget} cannot hold "
                    f"a speculative round: max_num_seqs {max_num_seqs} x "
                    f"(spec_tokens {self.spec_tokens} + 1) needs {need} "
                    f"packed query tokens")
        if num_pages is None:
            # default: every row slot can hold a max_len sequence, so
            # preemption never fires unless the operator shrinks the pool
            num_pages = max_num_seqs * self.max_pages_per_seq + 1
        if kv_cache_dtype in ("int8", jnp.int8, jnp.dtype(jnp.int8)):
            dtype = jnp.int8          # int8 pool: ~2x sequences per byte
        elif kv_cache_dtype is not None:
            dtype = jnp.dtype(kv_cache_dtype)
        else:
            dtype = self.params["embed"].dtype
        # two-tier KV (serving/kv_tier.py, ROADMAP 5a): host_kv_pages >
        # 0 backs the HBM pool with a host-RAM spill arena — preemption
        # victims PARK (exact-byte spill/restore) instead of
        # recomputing, live context is bounded by hbm + host pages, and
        # a background staging thread prefetches parked sequences back
        # ahead of re-admission. kv_prefetch=False is the injected
        # regression hook: every restore then stages synchronously and
        # counts as a kv_prefetch_stall.
        self._kv_prefetch_depth = max(int(kv_prefetch_depth), 1)
        if host_kv_pages and int(host_kv_pages) > 0:
            from .kv_tier import TieredKVPool
            self.pool = TieredKVPool(
                cfg.num_hidden_layers, cfg.num_key_value_heads,
                cfg.head_dim, num_pages=num_pages, page_size=page_size,
                host_pages=int(host_kv_pages), dtype=dtype,
                high_watermark=high_watermark,
                low_watermark=low_watermark,
                pinned_page_budget=pinned_prefix_pages, mesh=self.mesh,
                prefetch=bool(kv_prefetch),
                prefetch_depth=self._kv_prefetch_depth,
                spill_seed=kv_spill_seed)
        else:
            self.pool = PagedKVPool(
                cfg.num_hidden_layers, cfg.num_key_value_heads,
                cfg.head_dim, num_pages=num_pages, page_size=page_size,
                dtype=dtype, high_watermark=high_watermark,
                low_watermark=low_watermark,
                pinned_page_budget=pinned_prefix_pages, mesh=self.mesh)
        self._tiered = hasattr(self.pool, "arena")
        # gauge_stale_after_s: snapshot-side staleness horizon — gauges
        # last set longer ago than this read as null (listed under
        # "stale_gauges") instead of as current values; the telemetry
        # scraper applies its own horizon independently
        self.metrics = ServingMetrics(now_fn=now_fn,
                                      stale_after_s=gauge_stale_after_s)
        # observability (serving/tracing.py): the per-request span
        # tracer is OPT-IN (None = zero per-request bookkeeping); the
        # flight recorder is ALWAYS ON — a bounded ring of step/fleet
        # events whose last-N context auto-dumps on InvariantViolation,
        # nonfinite-logits aborts, and (cluster) replica crashes. Both
        # are host-side appends stamped on now_fn: they add zero jitted
        # dispatches and zero device syncs (tests/test_tracing.py gates
        # the trace-count and dispatch ratios with tracing enabled).
        from .tracing import FlightRecorder
        self.tracer = tracer
        self.flight = flight_recorder if flight_recorder is not None \
            else FlightRecorder(flight_capacity)
        #: replica id under a ClusterEngine (fleet flight entries carry
        #: it); None for a standalone engine
        self.engine_id = engine_id
        # a failing pool audit raises InvariantViolation WITH the
        # flight recorder's last-N context attached (kv_cache.py reads
        # these back-references at raise time; the counter keeps
        # metrics.flight_dumps honest for audit-triggered dumps too)
        self.pool.flight_recorder = self.flight
        self.pool.flight_dump_counter = self.metrics.flight_dumps
        self.scheduler = Scheduler(
            self.pool,
            SchedulerConfig(max_num_seqs=max_num_seqs,
                            chunk_size=chunk_size, q_block=q_block,
                            step_token_budget=step_token_budget,
                            max_prefills_per_step=max_prefills_per_step,
                            now_fn=now_fn),
            self.max_pages_per_seq, metrics=self.metrics)
        self.max_num_seqs = self.scheduler.config.max_num_seqs
        self.q_block = self.scheduler.config.q_block
        self.step_token_budget = self.scheduler.config.step_token_budget
        # remember whether the caller PINNED the execution mode: the
        # megakernel honors an explicit knob but otherwise stays
        # env-driven (jnp fallback off-TPU, int8_matmul's discipline)
        self._interpret_explicit = interpret is not None
        if interpret is None:
            from ..kernels import _on_tpu
            interpret = not _on_tpu()
        self._interpret = interpret
        self._now = now_fn
        self._stream_cb = stream_cb
        #: every sampling draw is a per-request stream folded off this
        #: one base key (models/generation.request_keys) — the engine
        #: never consumes shared key state, so batch composition cannot
        #: perturb any request's draws
        self._base_key = jax.random.key(seed)
        self._draft = None
        if self.spec_tokens > 0:
            from .spec_decode import DraftWorker
            if draft_num_pages is None:
                # the draft holds every running row's FULL context with
                # no prefix sharing and no preemption of its own — size
                # it for the no-sharing worst case, independent of how
                # starved the operator made the target pool (draft pages
                # are small-model bytes; explicit draft_num_pages
                # overrides)
                draft_num_pages = \
                    self.max_num_seqs * self.max_pages_per_seq + 1
            self._draft = DraftWorker(
                draft_model, target_cfg=cfg, page_size=page_size,
                max_num_seqs=self.max_num_seqs,
                max_pages_per_seq=self.max_pages_per_seq,
                num_pages=draft_num_pages,
                step_token_budget=self.step_token_budget,
                q_block=self.q_block, chunk_size=self.chunk_size,
                seed=seed, quantized_mode=draft_quantized_mode,
                interpret=interpret if self._interpret_explicit else None)
        self._ids = itertools.count()
        self._seqs: dict[str, Sequence] = {}
        self._outputs: dict[str, RequestOutput] = {}
        self.prefix_caching = prefix_caching
        self.prefix_cache_size = prefix_cache_size
        #: token-chain -> (donor seq_id, chain length); valid while the
        #: donor still owns the chain's pages (it leaves the map's truth
        #: when the donor is freed — the probe re-validates on every hit)
        self._prefix_cache: dict[tuple, tuple[str, int]] = {}
        #: page-aligned token-prefix -> (pinned chain id, length): the
        #: pinned-LRU fallback when no LIVE donor matches — a chain the
        #: pool still pins can be re-forked long after its last sequence
        #: sharer left (repeated cold prompts skip the re-prefill). LRU
        #: capped alongside _prefix_cache; entries whose chain the pool
        #: evicted fail ``is_pinned`` and are pruned on probe.
        self._pinned_index: dict[tuple, tuple[tuple, int]] = {}
        # persistent cross-restart prefix store (io/persist.py): pinned
        # prefix chains — pages, int8 scales, and the token-chain index
        # — survive process death. Construction WARM-RELOADS whatever
        # the store holds (corrupt/missing degrades to a cold start with
        # a restore_fallbacks count + flight event, never an exception;
        # a geometry/dtype drift raises PrefixStoreMismatch); afterwards
        # every pin-set change re-persists the chains (autosave), so a
        # crashed replica's successor re-forks fleet-wide shared system
        # prompts instead of paying the re-prefill TTFT cliff.
        self.prefix_store = None
        self._prefix_autosave = False
        self._prefix_store_sig = frozenset()
        if prefix_store is not None:
            if isinstance(prefix_store, (str, os.PathLike)):
                from ..io.persist import ArtifactStore
                prefix_store = ArtifactStore(
                    prefix_store, flight_recorder=self.flight,
                    now_fn=self._now)
            self.prefix_store = prefix_store
            self._prefix_autosave = True if prefix_store_autosave is None \
                else bool(prefix_store_autosave)
            self._restore_prefix_store()
        #: fleet-wide prefix cache (serving/fabric.py FleetPrefixCache,
        #: cluster-scope, shared by every replica): chains this engine
        #: pins publish into it, and the admission probe falls back to
        #: it when no local donor or pinned chain matches — a prompt
        #: prefilled once anywhere in the fleet is never re-prefilled
        #: here, even if the publishing replica has since crashed.
        self.fleet_prefix = fleet_prefix_cache
        # multi-tenant LoRA serving (paddle_tpu.tenancy): a
        # fixed-capacity adapter slab whose slot ids travel the ragged
        # step as per-token DATA (slot 0 = zeros = the base model), and
        # an optional per-tenant economy — weighted-fair admission,
        # refilling token quotas, cost ledgers. Both are strictly
        # additive: without them the step's operand list gains NOTHING
        # (None legs are empty pytrees) and admission stays bare FIFO.
        self.adapters = None
        if adapter_slots:
            from ..tenancy.adapters import AdapterRegistry
            self.adapters = AdapterRegistry(
                cfg, n_slots=int(adapter_slots), rank=int(adapter_rank))
        self.tenant_policy = None
        if tenants is not None:
            from ..tenancy.policy import TenantPolicy
            if isinstance(tenants, TenantPolicy):
                self.tenant_policy = tenants
            else:
                self.tenant_policy = TenantPolicy(tenants,
                                                  now_fn=self._now)
            self.scheduler.policy = self.tenant_policy
        #: wall/virtual time of the last per-step cost accrual (KV
        #: byte-seconds, adapter-slot-seconds); None until the first step
        self._last_cost_t = None
        # persistent adapter store (io/persist.py): published adapters
        # survive process death — construction warm-reloads the newest
        # verified version (corruption degrades to a cold start inside
        # ArtifactStore; geometry drift raises AdapterStoreMismatch),
        # and every add/evict re-persists when autosave is on.
        self.adapter_store = None
        self._adapter_autosave = False
        if adapter_store is not None:
            if isinstance(adapter_store, (str, os.PathLike)):
                from ..io.persist import ArtifactStore
                adapter_store = ArtifactStore(
                    adapter_store, flight_recorder=self.flight,
                    now_fn=self._now)
            self.adapter_store = adapter_store
            self._adapter_autosave = True if adapter_store_autosave \
                is None else bool(adapter_store_autosave)
            restored = self.adapters.restore(self.adapter_store)
            if restored:
                self.metrics.adapter_restores.inc(restored)
                self.record_fleet_event("adapter_restore",
                                        adapters=restored)
        # the params the TWO step executables trace over: model scope
        # stacks the per-layer dicts into one [L, ...] LayerStack tree
        # ONCE here (fp arrays and int8 QuantizedWeight leaves alike);
        # self.params stays per-layer for everything host-side
        # (prefix/persist export, megakernel_mode probing)
        from ..kernels.decode_megakernel import stack_layer_params
        if self.megakernel_scope == "model":
            self._step_params = dict(
                self.params,
                layers=stack_layer_params(self.params["layers"]))
        else:
            self._step_params = self.params
        # fused ragged prefill (FLAGS_prefill_megakernel): the RAGGED
        # step traces over concat-fused projection weights (qkv, gate|up
        # — column-exact for fp and int8 alike) while the burst step
        # keeps the per-projection tree it scans today. int4/mixed
        # layouts have no fused geometry: fall back to the unfused
        # bodies and report it honestly (prefill_megakernel_mode).
        self._fused_layers = None
        if self.prefill_megakernel == "fused":
            from ..kernels.prefill_megakernel import fuse_layer_weights
            fused = [fuse_layer_weights(l) for l in self.params["layers"]]
            if any(f is None for f in fused):
                self.prefill_megakernel = "unfused"
            else:
                self._fused_layers = fused
        if self._fused_layers is not None:
            layers = self._fused_layers
            if self.megakernel_scope == "model":
                layers = stack_layer_params(layers)
            self._ragged_params = dict(self.params, layers=layers)
        else:
            self._ragged_params = self._step_params
        self._step_launched = False
        self._burst_launched = False
        self._build_step()

    # ------------------------------------------------------------------
    # the ONE jitted step (fixed shapes: any traffic mix, one executable)
    # ------------------------------------------------------------------
    def _build_step(self):
        cfg = self.cfg
        ps = self.page_size
        qb = self.q_block
        T = self.step_token_budget
        R = self.max_num_seqs
        PPS = self.max_pages_per_seq
        # a speculative row appends spec_tokens+1 tokens in one round:
        # the segmented int8 append's touched-page bound must cover it
        chunk_cap = max(self.chunk_size, self.spec_tokens + 1)
        K = self.spec_tokens
        interpret = self._interpret
        # the megakernel's mode: an explicit LLMEngine(interpret=...)
        # pins it (both launch paths then obey one knob); None stays
        # env-driven — Pallas on TPU, jnp fallback off it, interpreter
        # under PADDLE_TPU_FORCE_PALLAS (int8_matmul's discipline)
        mk_interpret = interpret if self._interpret_explicit else None
        quant_pool = self.pool.quantized
        H, Hkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        scope = self.megakernel_scope
        num_layers = cfg.num_hidden_layers
        prefill_fused = self.prefill_megakernel == "fused"

        def ragged_step(params, kv, kv_scales, tokens, positions, tbls,
                        q_starts, q_lens, kv_lens, sample_idx, temps,
                        top_ks, top_ps, seeds, sample_pos, spec_lens,
                        draft_tokens, draft_probs, base_key,
                        adapters, adapter_slots):
            # tokens/positions [T] packed row-wise (pad rows: q_len=0,
            # q_start=T); tbls [R, PPS]; kv_lens = committed + q_len per
            # row (the attention length AFTER this step's appends);
            # sample_idx [R, K+1] flat indices of each row's verify
            # positions (ordinary rows: K+1 copies of the last live
            # token). Sampling is fully in-graph: per-row knobs
            # (temps/top_ks/top_ps), per-request PRNG streams
            # (seeds/sample_pos off base_key), and — on speculative
            # rounds — the rejection sampler over the draft's candidates
            # (spec_lens/draft_tokens/draft_probs; all-zero on ordinary
            # rounds, where the sampler degenerates to one direct draw
            # from the last position's distribution).
            # adapters/adapter_slots (paddle_tpu.tenancy): the LoRA
            # slab pytree + per-token slot ids. None legs contribute
            # ZERO operands (empty pytrees), so adapter-free engines
            # lower byte-identical HLO; with a registry, which adapter
            # a token wears is a gather — data, never shape.
            tok_row = live = pre = None
            if prefill_fused:
                # the layer-invariant ragged prologue, hoisted: rope
                # phase tables, the page-slot scatter map, the packed
                # row/liveness masks and the attention block-row map
                # are computed ONCE per step and shared by every fused
                # layer body (value-identical to the per-layer
                # derivations — bitwise-neutral for the tokens)
                from ..kernels.prefill_megakernel import ragged_prologue
                pre = ragged_prologue(
                    positions, tbls, q_starts, q_lens,
                    theta=cfg.rope_theta, head_dim=d, page_size=ps,
                    max_pages=PPS, q_block=qb)
            else:
                tok_row, live = _ragged_packing(q_starts, q_lens, T)

            def lo(ad, p):
                if ad is None:
                    return None
                A, B = ad[p]
                return (A, B, adapter_slots)

            def fp_layer(lyr, ad, h, Kp, Vp):
                if prefill_fused:
                    from ..kernels.prefill_megakernel import \
                        fused_prefill_layer
                    h, Kp, Vp, _, _ = fused_prefill_layer(
                        lyr, h, Kp, Vp, tbls, pre, q_starts, q_lens,
                        kv_lens, eps=cfg.rms_norm_eps, num_heads=H,
                        q_block=qb, interpret=mk_interpret,
                        attn_interpret=interpret, adapters=ad,
                        slots=adapter_slots, scope=scope,
                        num_layers=num_layers)
                    return h, Kp, Vp
                # the shared fp layer body (spec_decode), which the
                # draft worker also runs — draft/target numerics come
                # from ONE definition
                return _ragged_fp_layer(
                    lyr, h, Kp, Vp, positions, tbls, tok_row, live,
                    q_starts, q_lens, kv_lens, cfg, ps, PPS, qb,
                    interpret, adapters=ad, slots=adapter_slots)

            def int8_layer(lyr, ad, h, Kp, Ks, Vp, Vs):
                if prefill_fused:
                    from ..kernels.prefill_megakernel import \
                        fused_prefill_layer

                    def qafn(Kp, Ks, Vp, Vs, kt, vt):
                        return _append_quant(Kp, Ks, Vp, Vs, kt, vt,
                                             tbls, q_starts, q_lens,
                                             kv_lens)
                    h2, Kp, Vp, Ks, Vs = fused_prefill_layer(
                        lyr, h, Kp, Vp, tbls, pre, q_starts, q_lens,
                        kv_lens, eps=cfg.rms_norm_eps, num_heads=H,
                        q_block=qb, interpret=mk_interpret,
                        attn_interpret=interpret, k_scales=Ks,
                        v_scales=Vs, quant_append_fn=qafn, adapters=ad,
                        slots=adapter_slots, scope=scope,
                        num_layers=num_layers)
                    return h2, Kp, Ks, Vp, Vs
                x = _rms_norm(h, lyr["ln1"], cfg.rms_norm_eps)
                q = _wmat(x, lyr["q"], lora=lo(ad, "q")) \
                    .reshape(1, T, H, d)
                k = _wmat(x, lyr["k"], lora=lo(ad, "k")) \
                    .reshape(1, T, Hkv, d)
                v = _wmat(x, lyr["v"], lora=lo(ad, "v")) \
                    .reshape(1, T, Hkv, d)
                q = _rope(q, positions[None], cfg.rope_theta, d)
                k = _rope(k, positions[None], cfg.rope_theta, d)
                kt = jnp.transpose(k[0], (1, 0, 2))         # [Hkv, T, d]
                vt = jnp.transpose(v[0], (1, 0, 2))
                Kp, Ks, Vp, Vs = _append_quant(
                    Kp, Ks, Vp, Vs, kt, vt, tbls, q_starts, q_lens,
                    kv_lens)
                o = ragged_paged_attention(
                    q[0], Kp, Vp, tbls, q_starts, q_lens, kv_lens,
                    q_block=qb, interpret=interpret,
                    k_scales=Ks, v_scales=Vs)
                h = h + _wmat(o.reshape(1, T, H * d), lyr["o"],
                              lora=lo(ad, "o"))
                x = _rms_norm(h, lyr["ln2"], cfg.rms_norm_eps)
                h = h + _wmat(
                    jax.nn.silu(_wmat(x, lyr["gate"],
                                      lora=lo(ad, "gate")))
                    * _wmat(x, lyr["up"], lora=lo(ad, "up")),
                    lyr["down"], lora=lo(ad, "down"))
                return h, Kp, Ks, Vp, Vs

            h = params["embed"][tokens][None]               # [1, T, hid]
            if scope == "model":
                # scan-over-layers: pools (and the LoRA slab views)
                # stack inside the jit, the SAME layer bodies as the
                # unrolled path run as the scan body — ONE layer-body
                # site in the lowered program, so the prologue/epilogue
                # chains (rms_norm->qkv->rope, o-proj->residual->mlp)
                # appear once instead of L times in the compiled HLO
                Kst = jnp.stack([K for K, _ in kv])
                Vst = jnp.stack([V for _, V in kv])
                ad_st = None
                if adapters is not None:
                    ad_st = jax.tree.map(lambda *xs: jnp.stack(xs),
                                         *adapters)
                if not quant_pool:
                    def layer_body(hc, xs):
                        lyr, ad, Kp, Vp = xs
                        hc, Kp, Vp = fp_layer(lyr, ad, hc, Kp, Vp)
                        return hc, (Kp, Vp)
                    h, (Kn, Vn) = jax.lax.scan(
                        layer_body, h, (params["layers"], ad_st, Kst,
                                        Vst))
                    new_kv = [(Kn[li], Vn[li])
                              for li in range(num_layers)]
                    new_scales = []
                else:
                    Kss = jnp.stack([a for a, _ in kv_scales])
                    Vss = jnp.stack([b for _, b in kv_scales])

                    def layer_body(hc, xs):
                        lyr, ad, Kp, Vp, Ks, Vs = xs
                        hc, Kp, Ks, Vp, Vs = int8_layer(lyr, ad, hc, Kp,
                                                        Ks, Vp, Vs)
                        return hc, (Kp, Vp, Ks, Vs)
                    h, (Kn, Vn, Ksn, Vsn) = jax.lax.scan(
                        layer_body, h, (params["layers"], ad_st, Kst,
                                        Vst, Kss, Vss))
                    new_kv = [(Kn[li], Vn[li])
                              for li in range(num_layers)]
                    new_scales = [(Ksn[li], Vsn[li])
                                  for li in range(num_layers)]
            else:
                new_kv, new_scales = [], []
                for li, (lyr, (Kp, Vp)) in enumerate(
                        zip(params["layers"], kv)):
                    ad = adapters[li] if adapters is not None else None
                    if not quant_pool:
                        h, Kp, Vp = fp_layer(lyr, ad, h, Kp, Vp)
                        new_kv.append((Kp, Vp))
                        continue
                    Ks, Vs = kv_scales[li]
                    h, Kp, Ks, Vp, Vs = int8_layer(lyr, ad, h, Kp, Ks,
                                                   Vp, Vs)
                    new_scales.append((Ks, Vs))
                    new_kv.append((Kp, Vp))
            h = _rms_norm(h, params["norm"], cfg.rms_norm_eps)
            verify = h[0, sample_idx.reshape(-1)]       # [R*(K+1), hid]
            logits = _logits(params, verify, cfg) \
                .reshape(R, K + 1, -1)                  # [R, K+1, V]
            # non-finite guard: one in-graph isfinite all-reduce per
            # ragged row over its verify logits — a NaN/Inf surfaces at
            # commit time as a per-row flag the host turns into a
            # structured abort, instead of argmax/categorical silently
            # sampling token 0 from garbage. Pad rows (q_len == 0)
            # always read finite: their logits are null-page noise.
            finite = jnp.all(jnp.isfinite(logits.reshape(R, -1)), axis=-1) \
                | (q_lens <= 0)
            out, n_out = speculative_sample(
                logits, draft_tokens, draft_probs, spec_lens, temps,
                top_ks, top_ps, base_key, seeds, sample_pos)
            return (out, n_out, finite, new_kv,
                    new_scales if quant_pool else None)

        def _append_quant(Kp, Ks, Vp, Vs, kt, vt, tbls, q_starts, q_lens,
                          kv_lens):
            # segmented int8 append: one running-amax requant per
            # touched (head, page) — a chunk costs pages-touched
            # iterations, not chunk-length iterations
            Kp, Ks = _segmented_quant_append(
                Kp, Ks, kt, tbls, q_starts, q_lens, kv_lens, ps, PPS,
                chunk_cap)
            Vp, Vs = _segmented_quant_append(
                Vp, Vs, vt, tbls, q_starts, q_lens, kv_lens, ps, PPS,
                chunk_cap)
            return Kp, Ks, Vp, Vs

        def burst_step(params, kv, kv_scales, tokens, kv_lens, tbls,
                       live0, caps, temps, top_ks, top_ps, seeds, gpos0,
                       eos_ids, n_steps, base_key):
            # the on-device token loop (decode megakernel mode): up to
            # burst_tokens sample -> KV append -> EOS/length gate
            # iterations inside ONE executable. Every row is a
            # caught-up decode row; block tables, the int8 running-amax
            # scales, and the per-row live mask all ride the loop
            # carry. n_steps (traced) bounds the trip count so every
            # burst size reuses the same compilation; eos_ids < 0 means
            # "no eos" for that row. Sampling draws come from the same
            # per-request (seed, generation position) streams as the
            # per-token path — a request's sampled tokens are identical
            # whether it was served per-token or in bursts.
            from ..kernels.decode_megakernel import (fused_decode_layer,
                                                     fused_decode_model)
            R = self.max_num_seqs
            B = self.burst_tokens
            rows = jnp.arange(R)
            out0 = jnp.zeros((R, B), jnp.int32)
            gen0 = jnp.zeros((R,), jnp.int32)
            if not quant_pool:
                kv_scales = ()
            if scope == "model":
                # stack the pools ONCE per burst (outside the token
                # loop); the while_loop then carries the stacked [L,
                # ...] layout and the scanned body indexes it in place —
                # the stack/unstack round-trip amortizes over the whole
                # burst instead of repeating per token
                kv = (jnp.stack([K for K, _ in kv]),
                      jnp.stack([V for _, V in kv]))
                if quant_pool:
                    kv_scales = (jnp.stack([a for a, _ in kv_scales]),
                                 jnp.stack([b for _, b in kv_scales]))

            def cond(c):
                i, live = c[0], c[5]
                return (i < n_steps) & jnp.any(live)

            def body(c):
                i, tokens, kv, kv_scales, kv_lens, live, gen, out, ok = c
                h = params["embed"][tokens]                  # [R, hid]
                pos = kv_lens                                # append slot
                page_idx = jnp.clip(pos // ps, 0, PPS - 1)
                # rows live at iteration start append this iteration's
                # token; rows that die below stop appending next round
                live_in = live
                page = jnp.where(live, tbls[rows, page_idx], NULL_PAGE)
                off = pos % ps
                att_len = pos + 1       # attention covers the new token
                if scope == "model":
                    # ONE launch for the whole model: the fused layer
                    # body scans over the stacked weights/pools; the
                    # pool writes stay caller-owned closures so they
                    # replay the layer-scope appends bit for bit
                    if quant_pool:
                        def quant_append_fn(Kp, Ks, Vp, Vs, kc, vc):
                            Kp, Ks = _quantized_append(
                                Kp, Ks, jnp.transpose(kc, (1, 0, 2)),
                                page, off, ps, live)
                            Vp, Vs = _quantized_append(
                                Vp, Vs, jnp.transpose(vc, (1, 0, 2)),
                                page, off, ps, live)
                            return Kp, Ks, Vp, Vs
                        h, Kn, Vn, Ksn, Vsn = fused_decode_model(
                            params["layers"], h, kv[0], kv[1], tbls,
                            att_len, eps=cfg.rms_norm_eps,
                            theta=cfg.rope_theta, num_heads=H,
                            self_kv=False, interpret=mk_interpret,
                            k_scales=kv_scales[0],
                            v_scales=kv_scales[1],
                            quant_append_fn=quant_append_fn)
                        new_kv = (Kn, Vn)
                        new_scales = (Ksn, Vsn)
                    else:
                        def append_fn(Kp, Vp, kc, vc):
                            slot = page * ps + off
                            npages = Kp.shape[1]
                            kt = jnp.transpose(kc, (1, 0, 2))
                            vt = jnp.transpose(vc, (1, 0, 2))
                            Kp = Kp.reshape(Hkv, npages * ps, d) \
                                .at[:, slot].set(kt) \
                                .reshape(Hkv, npages, ps, d)
                            Vp = Vp.reshape(Hkv, npages * ps, d) \
                                .at[:, slot].set(vt) \
                                .reshape(Hkv, npages, ps, d)
                            return Kp, Vp
                        h, Kn, Vn, _, _ = fused_decode_model(
                            params["layers"], h, kv[0], kv[1], tbls,
                            att_len, eps=cfg.rms_norm_eps,
                            theta=cfg.rope_theta, num_heads=H,
                            self_kv=True, interpret=mk_interpret,
                            append_fn=append_fn)
                        new_kv = (Kn, Vn)
                        new_scales = None
                    hn = _rms_norm(h[None], params["norm"],
                                   cfg.rms_norm_eps)[0]
                    logits = _logits(params, hn, cfg)        # [R, V]
                    ok = ok & (jnp.all(jnp.isfinite(logits), axis=-1)
                               | ~live_in)
                    keys = request_keys(base_key, seeds, gpos0 + gen,
                                        FINAL_TAG)
                    nxt = sample_rows(logits, keys, temps, top_ks,
                                      top_ps)
                    out = out.at[:, i].set(jnp.where(live, nxt, 0))
                    gen = gen + live.astype(jnp.int32)
                    hit_eos = live & (eos_ids >= 0) & (nxt == eos_ids)
                    live = live & ~hit_eos & (gen < caps)
                    kv_lens = kv_lens + live_in.astype(jnp.int32)
                    tokens = jnp.where(live_in, nxt, tokens)
                    return (i + 1, tokens, new_kv,
                            new_scales if quant_pool else kv_scales,
                            kv_lens, live, gen, out, ok)
                new_kv, new_scales = [], []
                for li, (lyr, (Kp, Vp)) in enumerate(
                        zip(params["layers"], kv)):
                    if quant_pool:
                        # append-first: the running-amax requant must be
                        # visible to the attention gather, so k/v are
                        # projected here, quantize-appended, and the
                        # megakernel attends over all att_len positions
                        x = _rms_norm(h[None], lyr["ln1"],
                                      cfg.rms_norm_eps)[0]
                        kc = _rope(_wmat(x, lyr["k"])
                                   .reshape(R, Hkv, d)[None],
                                   pos[None], cfg.rope_theta, d)[0]
                        vc = _wmat(x, lyr["v"]).reshape(R, Hkv, d)
                        Ks, Vs = kv_scales[li]
                        Kp, Ks = _quantized_append(
                            Kp, Ks, jnp.transpose(kc, (1, 0, 2)), page,
                            off, ps, live)
                        Vp, Vs = _quantized_append(
                            Vp, Vs, jnp.transpose(vc, (1, 0, 2)), page,
                            off, ps, live)
                        new_scales.append((Ks, Vs))
                        h, _, _ = fused_decode_layer(
                            lyr, h, Kp, Vp, tbls, att_len,
                            eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
                            num_heads=H, self_kv=False,
                            interpret=mk_interpret, k_scales=Ks,
                            v_scales=Vs)
                    else:
                        # the megakernel computes this token's k/v
                        # in-kernel (self-attention term in-register)
                        # and returns them for the page scatter —
                        # lossless for fp pools
                        h, kc, vc = fused_decode_layer(
                            lyr, h, Kp, Vp, tbls, att_len,
                            eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
                            num_heads=H, self_kv=True,
                            interpret=mk_interpret)
                        slot = page * ps + off
                        npages = Kp.shape[1]
                        kt = jnp.transpose(kc, (1, 0, 2))    # [Hkv, R, d]
                        vt = jnp.transpose(vc, (1, 0, 2))
                        Kp = Kp.reshape(Hkv, npages * ps, d).at[:, slot] \
                            .set(kt).reshape(Hkv, npages, ps, d)
                        Vp = Vp.reshape(Hkv, npages * ps, d).at[:, slot] \
                            .set(vt).reshape(Hkv, npages, ps, d)
                    new_kv.append((Kp, Vp))
                hn = _rms_norm(h[None], params["norm"],
                               cfg.rms_norm_eps)[0]
                logits = _logits(params, hn, cfg)            # [R, V]
                # the per-row isfinite guard, burst edition: a row whose
                # logits go non-finite at ANY loop iteration is flagged;
                # the host aborts it at the burst boundary rather than
                # committing tokens sampled from garbage
                ok = ok & (jnp.all(jnp.isfinite(logits), axis=-1)
                           | ~live_in)
                keys = request_keys(base_key, seeds, gpos0 + gen,
                                    FINAL_TAG)
                nxt = sample_rows(logits, keys, temps, top_ks, top_ps)
                out = out.at[:, i].set(jnp.where(live, nxt, 0))
                gen = gen + live.astype(jnp.int32)
                hit_eos = live & (eos_ids >= 0) & (nxt == eos_ids)
                live = live & ~hit_eos & (gen < caps)
                kv_lens = kv_lens + live_in.astype(jnp.int32)
                tokens = jnp.where(live_in, nxt, tokens)
                return (i + 1, tokens, new_kv,
                        tuple(new_scales) if quant_pool else kv_scales,
                        kv_lens, live, gen, out, ok)

            init = (jnp.asarray(0, jnp.int32), tokens, kv,
                    tuple(kv_scales), kv_lens, live0, gen0, out0,
                    jnp.ones((R,), bool))
            c = jax.lax.while_loop(cond, body, init)
            if scope == "model":
                # unstack the carried [L, ...] pools back into the
                # pool's per-layer list layout (host code indexes it)
                Kn, Vn = c[2]
                new_kv = [(Kn[li], Vn[li]) for li in range(num_layers)]
                if quant_pool:
                    Ksn, Vsn = c[3]
                    new_scales = [(Ksn[li], Vsn[li])
                                  for li in range(num_layers)]
                else:
                    new_scales = None
                return (c[7], c[6], c[8], new_kv, new_scales)
            return (c[7], c[6], c[8], c[2],
                    list(c[3]) if quant_pool else None)

        # donate the pool buffers (args 1-2: pages + scales) so the step
        # updates in place on TPU; CPU/PJRT-cpu ignores donation with a
        # warning, so skip there
        from ..kernels import _on_tpu
        donate = (1, 2) if _on_tpu() else ()
        self._ragged_jit = jax.jit(ragged_step, donate_argnums=donate)
        self._burst_jit = jax.jit(burst_step, donate_argnums=donate)
        # ordinary rounds of a spec-enabled engine still feed the fixed
        # (R, K[, V]) draft operands — build the all-zero versions ONCE
        # instead of allocating + shipping R*K*V float zeros per step
        self._zero_draft = (
            jnp.zeros((self.max_num_seqs, K), jnp.int32),
            jnp.zeros((self.max_num_seqs, K, cfg.vocab_size),
                      jnp.float32))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def add_request(self, prompt_token_ids, *, max_new_tokens=16,
                    temperature=0.0, top_k=None, top_p=None, seed=None,
                    eos_token_id=None, deadline_s=None, abort_after_s=None,
                    request_id=None, tenant_id=None, adapter_id=None):
        """Queue a request; returns its id. Accepts a Request too.

        ``top_k``/``top_p``/``seed`` are per-request sampling state: the
        knobs travel as per-row DATA through the one jitted step, and
        every random draw the request consumes is a pure function of
        ``(seed, generation position)`` — so a fixed (seed, prompt)
        reproduces the same sampled tokens bit for bit regardless of
        what it is co-scheduled with. ``seed=None`` derives a stable
        seed from the request_id.

        An unserviceable request (prompt + max_new_tokens over max_len or
        over the pool's page limit) raises :class:`RequestRejected` AFTER
        recording a finalized aborted output under its id — the serving
        loop and every other in-flight request keep running.
        """
        if isinstance(prompt_token_ids, Request):
            r = prompt_token_ids
            return self.add_request(
                r.prompt_token_ids, max_new_tokens=r.max_new_tokens,
                temperature=r.temperature, top_k=r.top_k, top_p=r.top_p,
                seed=r.seed, eos_token_id=r.eos_token_id,
                deadline_s=r.deadline_s, abort_after_s=r.abort_after_s,
                request_id=r.request_id, tenant_id=r.tenant_id,
                adapter_id=r.adapter_id)
        prompt = [int(t) for t in np.asarray(prompt_token_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if top_k is not None and int(top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        rid = request_id or f"req-{next(self._ids)}"
        if rid in self._seqs or rid in self._outputs:
            raise KeyError(f"duplicate request_id {rid!r}")
        total = len(prompt) + max_new_tokens
        needed = self.pool.pages_for(total)
        limit = min(self.pool.capacity, self.max_pages_per_seq)
        if total > self.max_len or needed > limit:
            self._outputs[rid] = RequestOutput(
                rid, prompt, status="aborted",
                finish_reason="rejected_oversize")
            self.metrics.rejected_requests.inc()
            raise RequestRejected(
                rid, "rejected_oversize", needed_pages=needed, limit=limit,
                message=(
                    f"request {rid}: prompt {len(prompt)} + "
                    f"max_new_tokens {max_new_tokens} needs {needed} pages "
                    f"(limit {limit}) / {total} tokens (max_len "
                    f"{self.max_len}) — rejected at admission"))
        # adapter resolution (paddle_tpu.tenancy): an explicit
        # adapter_id wins; None falls back to the tenant's declared
        # default (or the base model). A request naming an adapter the
        # registry does not hold is REJECTED with a structured output
        # — serving it the base model silently would be a correctness
        # bug, not a degradation.
        if adapter_id is None:
            adapter_id = self.tenant_policy.adapter_for(tenant_id) \
                if self.tenant_policy is not None else 0
        adapter_slot = 0
        if adapter_id not in (0, None):
            from ..tenancy.adapters import UnknownAdapter
            try:
                if self.adapters is None:
                    raise UnknownAdapter(adapter_id)
                adapter_slot = self.adapters.acquire(adapter_id)
            except UnknownAdapter:
                self._outputs[rid] = RequestOutput(
                    rid, prompt, status="aborted",
                    finish_reason="rejected_unknown_adapter")
                self.metrics.rejected_requests.inc()
                raise RequestRejected(
                    rid, "rejected_unknown_adapter",
                    message=(
                        f"request {rid}: adapter {adapter_id!r} is not "
                        f"in the registry "
                        f"({self.adapters.adapter_ids() if self.adapters is not None else 'no registry'}) "
                        f"— publish it (engine.add_adapter / "
                        f"AdapterTuner.publish) before submitting"))
        else:
            adapter_id = 0
        now = self._now()
        seq = Sequence(
            seq_id=rid, prompt_ids=prompt, max_new_tokens=max_new_tokens,
            arrival=now,
            deadline=None if deadline_s is None else now + deadline_s,
            abort_deadline=None if abort_after_s is None
            else now + abort_after_s,
            temperature=temperature,
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p),
            # seeds ride an int32 operand array: mask wide seeds into
            # range instead of blowing up the serving loop at launch
            seed=((int(seed) & 0x7FFFFFFF) if seed is not None
                  else zlib.crc32(str(rid).encode("utf-8")) & 0x7FFFFFFF),
            eos_token_id=eos_token_id, tenant_id=tenant_id,
            adapter_id=adapter_id, adapter_slot=adapter_slot)
        self.scheduler.add(seq)
        self._seqs[rid] = seq
        self._outputs[rid] = RequestOutput(rid, prompt)
        self.metrics.requests_added.inc()
        self._trace(rid, "enqueue", t=now, prompt_len=len(prompt),
                    max_new_tokens=int(max_new_tokens))
        return rid

    def cancel(self, request_id) -> bool:
        """Gracefully cancel: frees pages if running, keeps the tokens
        streamed so far in the output. Returns False if already done."""
        seq = self.scheduler.remove(request_id)
        if seq is None:
            return False
        self._finalize(seq, "cancelled")
        self.metrics.cancelled_requests.inc()
        return True

    def withdraw(self, request_id) -> bool:
        """Remove a WAITING request entirely — the cluster router's
        drain path (serving/cluster.py): the request is requeued onto a
        surviving replica, so THIS engine must forget it without
        recording a terminal output (unlike :meth:`cancel`). Returns
        False for unknown, running, or already-resolved requests —
        running rows stay to finish their drain."""
        seq = self._seqs.get(request_id)
        if seq is None or seq.status is not SequenceStatus.WAITING:
            return False
        if not any(s is seq for s in self.scheduler.waiting):
            return False
        if seq.seq_id in self.pool:
            # a PARKED sequence (two-tier pools) owns pages and streamed
            # tokens: requeueing it elsewhere would silently drop both —
            # it stays to finish its drain, exactly like a running row
            return False
        self.scheduler.waiting = type(self.scheduler.waiting)(
            s for s in self.scheduler.waiting if s is not seq)
        if self._draft is not None:
            self._draft.drop(request_id)
        if self.adapters is not None and seq.adapter_id not in (0, None):
            self.adapters.release(seq.adapter_id)
        del self._seqs[request_id]
        del self._outputs[request_id]
        return True

    # ------------------------------------------------------------------
    # disaggregated serving: KV handoff (serving/fabric.py KVFabric)
    # ------------------------------------------------------------------
    def extract_request(self, request_id) -> dict:
        """Pull a caught-up RUNNING request out of this engine for a
        prefill->decode handoff: its committed KV pages leave as the
        host-side layers wire format, its row slot and pages free
        IMMEDIATELY (the prefill-pool win — the slot takes the next
        prompt while the request decodes elsewhere), and the returned
        payload is everything :meth:`inject_request` needs to resume it
        bit-identically on another replica. Only a caught-up row
        (``uncached_len == 1`` with at least the first token sampled)
        extracts — mid-prefill rows keep chunking here."""
        seq = self._seqs.get(request_id)
        if seq is None:
            raise KeyError(f"unknown request {request_id!r}")
        if seq.status is not SequenceStatus.RUNNING \
                or seq.uncached_len != 1 or not seq.tokens:
            raise ValueError(
                f"request {request_id!r} is not a caught-up decode row "
                f"(status={seq.status.value}, uncached={seq.uncached_len}, "
                f"tokens={len(seq.tokens)}) — not extractable")
        num_tokens, layers = self.pool.export_pages(request_id,
                                                    seq.cached_len)
        self.scheduler.running.remove(seq)
        self.pool.free(request_id)
        if self._draft is not None:
            self._draft.drop(request_id)
        if self.adapters is not None and seq.adapter_id not in (0, None):
            self.adapters.release(seq.adapter_id)
        del self._seqs[request_id]
        del self._outputs[request_id]
        self.flight.record("handoff_out", self._now(), request=request_id,
                           pages=self.pool.pages_for(num_tokens))
        return {"request_id": request_id,
                "prompt_ids": list(seq.prompt_ids),
                "tokens": list(seq.tokens),
                "max_new_tokens": seq.max_new_tokens,
                "arrival": seq.arrival,
                "deadline": seq.deadline,
                "abort_deadline": seq.abort_deadline,
                "temperature": seq.temperature,
                "top_k": seq.top_k, "top_p": seq.top_p,
                "seed": seq.seed, "eos_token_id": seq.eos_token_id,
                "num_preemptions": seq.num_preemptions,
                "first_token_at": seq.first_token_at,
                "tenant_id": seq.tenant_id,
                "adapter_id": seq.adapter_id,
                "cached_len": seq.cached_len,
                "num_tokens": num_tokens, "layers": layers}

    def inject_request(self, payload: dict) -> str:
        """Land an extracted request on THIS engine. The transferred
        pages adopt into the pool (two-tier pools stage them in the
        host arena as a PARKED sequence, so re-admission rides the
        cursor-ahead prefetch path; single-tier pools land them in HBM
        directly) and the sequence enqueues as a caught-up decode row —
        its next sampled token is a pure function of (seed, position),
        so the handoff is invisible in the token stream. Counted on
        ``kv_pages_transferred``."""
        rid = payload["request_id"]
        if rid in self._seqs or rid in self._outputs:
            raise KeyError(f"duplicate request_id {rid!r}")
        cached_len = int(payload["cached_len"])
        if int(payload["num_tokens"]) != cached_len:
            raise ValueError(
                f"request {rid!r}: payload carries "
                f"{payload['num_tokens']} tokens of KV but cached_len is "
                f"{cached_len}")
        adapter_id = payload.get("adapter_id") or 0
        adapter_slot = 0
        if adapter_id not in (0, None):
            from ..tenancy.adapters import UnknownAdapter
            if self.adapters is None:
                raise UnknownAdapter(adapter_id)
            adapter_slot = self.adapters.acquire(adapter_id)
        self.pool.adopt_sequence(rid, cached_len, payload["layers"])
        seq = Sequence(
            seq_id=rid, prompt_ids=list(payload["prompt_ids"]),
            max_new_tokens=payload["max_new_tokens"],
            arrival=payload["arrival"], deadline=payload["deadline"],
            abort_deadline=payload["abort_deadline"],
            temperature=payload["temperature"],
            top_k=payload["top_k"], top_p=payload["top_p"],
            seed=payload["seed"], eos_token_id=payload["eos_token_id"],
            num_preemptions=payload["num_preemptions"],
            tenant_id=payload.get("tenant_id"),
            adapter_id=adapter_id, adapter_slot=adapter_slot)
        try:
            self.scheduler.add(seq)
        except ValueError:
            self.pool.free(rid)
            if self.adapters is not None and adapter_id not in (0, None):
                self.adapters.release(adapter_id)
            raise
        # carried progress: add() enqueues a WAITING row; these fields
        # make it a caught-up decode row the parked-admission path
        # restores instead of re-prefilling
        seq.tokens = list(payload["tokens"])
        seq.cached_len = cached_len
        seq.first_token_at = payload["first_token_at"]
        self._seqs[rid] = seq
        self._outputs[rid] = RequestOutput(
            rid, list(seq.prompt_ids), token_ids=list(seq.tokens),
            status=seq.status.value, num_preemptions=seq.num_preemptions)
        n_pages = self.pool.pages_for(cached_len)
        self.metrics.kv_pages_transferred.inc(n_pages)
        self.flight.record("handoff_in", self._now(), request=rid,
                           pages=n_pages)
        return rid

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    def outputs(self) -> dict:
        return dict(self._outputs)

    def release(self, request_id) -> "RequestOutput":
        """Drop a RESOLVED request's retained state (the client has
        consumed its output). A long-running server must call this (or
        use stream_cb and release on the finished event) — the engine
        retains finished outputs until released so polling clients can
        always fetch them."""
        out = self._outputs.get(request_id)
        if out is None:
            raise KeyError(f"unknown request {request_id!r}")
        if not out.finished:
            raise ValueError(
                f"request {request_id!r} is still {out.status}; "
                f"cancel() it before release()")
        del self._outputs[request_id]
        self._seqs.pop(request_id, None)
        return out

    # ------------------------------------------------------------------
    # observability (serving/tracing.py)
    # ------------------------------------------------------------------
    def _trace(self, rid, kind, t=None, **detail):
        """Record one request span when a tracer is attached — a plain
        host-side append stamped on now_fn; no-op (one attribute read)
        without a tracer."""
        if self.tracer is not None:
            self.tracer.span(rid, kind, self._now() if t is None else t,
                             **detail)

    def record_fleet_event(self, kind, **detail):
        """Engine-scope event onto the flight recorder (always) and the
        tracer's event stream (when attached) — degradation rung moves,
        fault effects, anything not owned by one request."""
        now = self._now()
        if self.engine_id is not None:
            detail.setdefault("engine", self.engine_id)
        self.flight.record(kind, now, **detail)
        if self.tracer is not None:
            self.tracer.event(kind, now, **detail)

    def flight_dump(self, reason, **detail) -> dict:
        """Snapshot the flight recorder's last-N events as a structured
        post-mortem (counted on ``metrics.flight_dumps``)."""
        if self.engine_id is not None:
            detail.setdefault("engine", self.engine_id)
        self.metrics.flight_dumps.inc()
        return self.flight.dump(reason, t=self._now(), **detail)

    def _zero_step_args(self):
        """Zero-filled ragged-step operands at the exact launch shapes
        (the AOT lowering surface — never dispatched)."""
        T, R, PPS = (self.step_token_budget, self.max_num_seqs,
                     self.max_pages_per_seq)
        K = self.spec_tokens
        z = jnp.zeros
        return (self._ragged_params, self.pool.kv, self.pool.kv_scales,
                z((T,), jnp.int32), z((T,), jnp.int32),
                jnp.full((R, PPS), NULL_PAGE, jnp.int32),
                jnp.full((R,), T, jnp.int32), z((R,), jnp.int32),
                z((R,), jnp.int32), z((R, K + 1), jnp.int32),
                z((R,), jnp.float32), z((R,), jnp.int32),
                jnp.ones((R,), jnp.float32), z((R,), jnp.int32),
                z((R,), jnp.int32), z((R,), jnp.int32),
                self._zero_draft[0], self._zero_draft[1], self._base_key,
                self.adapters.slab if self.adapters is not None else None,
                z((T,), jnp.int32) if self.adapters is not None else None)

    def _zero_burst_args(self):
        """Zero-filled burst-step operands at the exact launch shapes."""
        R, PPS = self.max_num_seqs, self.max_pages_per_seq
        z = jnp.zeros
        return (self._step_params, self.pool.kv, self.pool.kv_scales,
                z((R,), jnp.int32), z((R,), jnp.int32),
                jnp.full((R, PPS), NULL_PAGE, jnp.int32),
                z((R,), bool), z((R,), jnp.int32), z((R,), jnp.float32),
                z((R,), jnp.int32), jnp.ones((R,), jnp.float32),
                z((R,), jnp.int32), z((R,), jnp.int32),
                jnp.full((R,), -1, jnp.int32),
                jnp.asarray(0, jnp.int32), self._base_key)

    def ragged_step_hlo(self):
        """Compiled HLO text of the ONE ragged-step executable, lowered
        AOT over zero-filled operands at the exact launch shapes — the
        fusion-forensics surface (tools/bench_probes.probe_hlo_fusion;
        jit/hlo_forensics.py parses it). Out-of-band by construction:
        the jit dispatch cache and the trace-count==1 gate are
        untouched."""
        return self._ragged_jit.lower(
            *self._zero_step_args()).compile().as_text()

    def ragged_step_lowering(self):
        """UNOPTIMIZED StableHLO of the ragged step — the launch-
        accounting surface (jit/hlo_forensics.launch_stats): a scanned
        layer loop appears as ONE body inside ``stablehlo.while``; the
        unrolled loop appears L times. Pre-optimization by design, so
        the count is the program's structure, not an XLA fusion
        decision."""
        return self._ragged_jit.lower(*self._zero_step_args()).as_text()

    def burst_step_lowering(self):
        """UNOPTIMIZED StableHLO of the burst executable (the on-device
        token loop), for the same launch accounting."""
        return self._burst_jit.lower(*self._zero_burst_args()).as_text()

    def launch_stats(self, burst=False, kinds=None):
        """jit/hlo_forensics.launch_stats over the step executable's
        unoptimized lowering, with this engine's marker constants
        supplied: the fp/int8 ragged layer bodies and the fp burst body
        carry 2 rms_norm (rsqrt) markers each, the int8 burst body
        carries 3 (the pre-append prologue norm), and the final norm is
        the single non-layer marker. ``burst=True`` accounts the burst
        executable, whose one invocation covers up to ``burst_tokens``
        tokens per row.

        ``kinds`` (a ``{name: markers_per_body}`` dict) routes to
        ``mixed_launch_stats`` instead: the ragged step is a MIXED
        invocation (prefill-chunk rows and decode rows share its one
        fixed shape), and the per-kind decomposition attributes the
        body sites — or refuses with ValueError when the marker algebra
        cannot, rather than fabricate a launch count. This engine's
        unified ragged body is one kind (``{"ragged": 2}``); separate
        prefill/decode bodies come from callers gluing programs."""
        from ..jit.hlo_forensics import launch_stats, mixed_launch_stats
        if kinds is not None:
            return mixed_launch_stats(
                self.burst_step_lowering() if burst
                else self.ragged_step_lowering(),
                num_layers=self.cfg.num_hidden_layers, kinds=kinds,
                tokens_per_invocation=self.burst_tokens if burst else 1)
        if burst:
            return launch_stats(
                self.burst_step_lowering(),
                num_layers=self.cfg.num_hidden_layers,
                markers_per_body=3 if self.pool.quantized else 2,
                tokens_per_invocation=self.burst_tokens)
        return launch_stats(
            self.ragged_step_lowering(),
            num_layers=self.cfg.num_hidden_layers,
            markers_per_body=2, tokens_per_invocation=1)

    def metrics_snapshot(self) -> dict:
        if self.adapters is not None:
            # registry counters fold in as deltas so repeated snapshots
            # never double-count a hot-add or eviction
            m = self.metrics
            m.adapter_hot_adds.inc(
                self.adapters.hot_adds - m.adapter_hot_adds.value)
            m.adapter_evictions.inc(
                self.adapters.evictions - m.adapter_evictions.value)
            m.adapter_evict_refusals.inc(
                self.adapters.evict_refusals
                - m.adapter_evict_refusals.value)
            m.adapter_slots_used.set(self.adapters.slots_used)
        snap = self.metrics.snapshot()
        snap["decode_cache_size"] = self.decode_cache_size()
        snap["burst_tokens"] = self.burst_tokens
        # tensor-parallel forensics: 1 = single-device engine
        snap["model_parallel_degree"] = self.pool.model_parallel_degree
        # two-tier KV forensics (kv_tier.py): per-tier page/byte budgets
        # — None for single-tier engines, so pre-tiering consumers see
        # explicit absence, never a fabricated zero-sized host tier
        snap["kv_hbm_pages"] = self.pool.capacity
        snap["kv_hbm_bytes"] = self.pool.pool_bytes
        if self._tiered:
            snap["kv_host_pages"] = self.pool.arena.capacity
            snap["kv_host_bytes"] = self.pool.host_bytes
            snap["kv_host_chain_promotions"] = \
                self.pool.host_chain_promotions
        else:
            snap["kv_host_pages"] = None
            snap["kv_host_bytes"] = None
            snap["kv_host_chain_promotions"] = None
        from ..kernels.decode_megakernel import megakernel_mode
        snap["megakernel_mode"] = megakernel_mode(
            self.params["layers"][0],
            interpret=self._interpret if self._interpret_explicit
            else None) if self.burst_tokens > 1 else None
        snap["megakernel_scope"] = self.megakernel_scope
        # fused ragged prefill forensics: the resolved flag plus the
        # honest kernel-tier report (Pallas / interpret / jnp fallback)
        # — "unfused" engines report mode None, never a fabricated tier
        snap["prefill_megakernel"] = self.prefill_megakernel
        if self._fused_layers is not None:
            from ..kernels.prefill_megakernel import \
                prefill_megakernel_mode
            snap["prefill_megakernel_mode"] = prefill_megakernel_mode(
                self._fused_layers[0],
                interpret=self._interpret if self._interpret_explicit
                else None)
        else:
            snap["prefill_megakernel_mode"] = None
        tok = snap["tokens_generated"]
        snap["host_dispatches_per_token"] = \
            snap["host_dispatches"] / tok if tok else None
        # speculative-decoding forensics: target launches per committed
        # token is the headline win (< 1.0 iff speculation pays), draft
        # trace count mirrors the engine's one-executable discipline
        snap["spec_tokens"] = self.spec_tokens
        snap["target_steps_per_token"] = \
            snap["decode_steps"] / tok if tok else None
        snap["draft_launches"] = \
            self._draft.launches if self._draft is not None else None
        snap["draft_decode_compiles"] = \
            self._draft.decode_cache_size() if self._draft is not None \
            else None
        # the k-step proposal loop is ONE scan executable (and one
        # launch per spec round) — the ROADMAP item 4 leftover's gate
        snap["draft_propose_compiles"] = \
            self._draft.propose_cache_size() if self._draft is not None \
            else None
        # multi-tenancy forensics: slab capacity + per-tenant ledgers —
        # explicit None for single-tenant engines, never fabricated zeros
        snap["adapter_slots"] = \
            self.adapters.n_slots if self.adapters is not None else None
        snap["tenants"] = \
            self.tenant_policy.snapshot() \
            if self.tenant_policy is not None else None
        return snap

    def decode_cache_size(self):
        """Actual XLA compile count of the ragged step — the compile gate
        asserts this stays 1 under ANY traffic mix (falls back to the
        launch-signature count when the jit cache is not introspectable).
        """
        try:
            return int(self._ragged_jit._cache_size())
        except Exception:
            return 1 if self._step_launched else 0

    def step(self):
        """One scheduler round: shed -> admit (prefix-fork) -> one
        device launch covering every running row. When every row is a
        caught-up decode row and ``burst_tokens > 1``, the launch is an
        on-device generation BURST (up to burst_tokens tokens per row,
        one host dispatch); otherwise it is one ragged step (decode
        steps and prefill chunks interleaved). Returns the
        RequestOutputs touched this step (admitted, token streamed,
        finished, shed, or preempted)."""
        touched = {}
        if self._tiered:
            # advance the pool's virtual round clock FIRST: a restore
            # this step claims at clock c, so a prefetch issued at the
            # END of the previous step (clock c-1) classifies as a hit
            # — the deterministic hit-vs-stall rule (kv_tier.py)
            self.pool.tick()
        for seq in self.scheduler.shed_expired():
            self._finalize(seq, "shed")
            touched[seq.seq_id] = self._outputs[seq.seq_id]
        # mid-flight SLO abort: running/waiting rows whose absolute e2e
        # deadline passed finalize HERE, at the step boundary — pages
        # freed through the normal finish path (CoW refcounts and
        # pinned chains intact), no more tokens decoded for them
        for seq in self.scheduler.abort_expired():
            self.metrics.deadline_aborts.inc()
            self._finalize(seq, "shed", reason="deadline_exceeded")
            touched[seq.seq_id] = self._outputs[seq.seq_id]
        if self.tenant_policy is not None:
            # quota shed: still-WAITING rows of tenants whose refilling
            # token bucket is exhausted beyond the grace window leave
            # with a structured reason instead of starving the queue
            for seq in self.scheduler.shed_quota():
                self.metrics.quota_shed_requests.inc()
                self.tenant_policy.count_shed(seq.tenant_id)
                self._finalize(seq, "shed",
                               reason=seq.shed_reason or "quota_exceeded")
                touched[seq.seq_id] = self._outputs[seq.seq_id]
        hook = self._prefix_probe if self.prefix_caching else None
        for seq in self.scheduler.admit(prefix_hook=hook):
            touched[seq.seq_id] = self._sync_output(seq)
            if self.tracer is not None:
                now = self._now()
                extra = {} if seq.tenant_id is None \
                    else {"tenant": seq.tenant_id}
                self._trace(
                    seq.seq_id, "admission", t=now,
                    prefix_shared=seq.cached_len,
                    queue_s=now - (seq.enqueued_at
                                   if seq.enqueued_at is not None
                                   else seq.arrival), **extra)
        plan = None
        bplan = None
        splan = None
        preempted = []
        if self._draft is not None and self.spec_enabled:
            # speculative round: eligible only when every running row is
            # a caught-up decode row (prompt chunks go through the
            # ordinary ragged path; the draft catches up lazily).
            # spec_enabled is the degradation ladder's runtime kill
            # switch: it gates ELIGIBILITY only — operand shapes (and
            # the one compiled executable) never change with it.
            splan = self.scheduler.prepare_spec(self.spec_tokens)
            preempted += self.scheduler.last_preempted
        if splan is None and self.burst_tokens > 1:
            bplan = self.scheduler.prepare_burst(self.burst_tokens)
            preempted += self.scheduler.last_preempted
        if splan is None and bplan is None:
            plan = self.scheduler.prepare_step()
            preempted += self.scheduler.last_preempted
        for t in preempted:
            if self._draft is not None:
                self._draft.drop(t.seq_id)  # recompute re-syncs from 0
            self._sync_output(t)           # surface fresh preemptions once
            touched[t.seq_id] = self._outputs[t.seq_id]
            self._trace(t.seq_id, "preempt",
                        num_preemptions=t.num_preemptions)
            self.flight.record("preempt", self._now(), request=t.seq_id)
        if splan is not None:
            if splan.cow_copies:
                self.metrics.cow_copies.inc(splan.cow_copies)
            if self._launch_spec(splan, touched):
                self.metrics.decode_steps.inc()
                self.metrics.ragged_pad_fraction.set(splan.pad_fraction)
            else:
                # the DRAFT pool could not serve the round (operator
                # under-sized draft_num_pages): speculation is demoted
                # to an ordinary decode round — target pressure
                # preempts, draft pressure must never kill the loop
                splan = None
                plan = self.scheduler.prepare_step()
                for t in self.scheduler.last_preempted:
                    self._draft.drop(t.seq_id)
                    self._sync_output(t)
                    touched[t.seq_id] = self._outputs[t.seq_id]
        if splan is None and bplan is not None:
            if bplan.cow_copies:
                self.metrics.cow_copies.inc(bplan.cow_copies)
            self._launch_burst(bplan, touched)
            self.metrics.decode_steps.inc()
            # pad fraction is a ragged-packing concept; zero it so the
            # gauge never freezes on a stale prefill step's value while
            # bursts serve the traffic
            self.metrics.ragged_pad_fraction.set(0.0)
        elif plan is not None:
            if plan.cow_copies:
                self.metrics.cow_copies.inc(plan.cow_copies)
            sampled, _, finite = self._launch(plan)
            step_prefill_rows = 0
            for i, (seq, q_start, q_len) in enumerate(plan.rows):
                if not finite[i]:
                    # NaN/Inf logits: the row's state (this step's KV
                    # appends included) is poison — abort the request
                    # with a structured error BEFORE any commit or
                    # prefix registration could propagate it
                    self._abort_nonfinite(seq)
                    touched[seq.seq_id] = self._outputs[seq.seq_id]
                    continue
                before = seq.cached_len
                seq.cached_len += q_len
                # a prefill-chunk row is one that committed prompt tokens
                # (incl. a 1-token final chunk) or any multi-token
                # recompute chunk; pure decode rows start caught-up past
                # the prompt
                if q_len > 1 or before < len(seq.prompt_ids):
                    self.metrics.prefill_chunks.inc()
                    step_prefill_rows += 1
                if self.prefix_caching and \
                        before < len(seq.prompt_ids) <= seq.cached_len:
                    self._register_prefix(seq)
                caught_up = seq.cached_len == seq.total_len
                if caught_up:
                    # the row is caught up: its sampled token is the next
                    # generated token. Mid-prompt chunks discard theirs.
                    self._commit_token(seq, int(sampled[i, 0]))
                if self.tracer is not None:
                    if q_len > 1 or before < len(seq.prompt_ids):
                        self._trace(seq.seq_id, "prefill_chunk",
                                    q_len=int(q_len),
                                    cached=int(seq.cached_len),
                                    new_tokens=1 if caught_up else 0,
                                    fused=self.prefill_megakernel
                                    == "fused")
                    else:
                        # a 1-token recompute row inside the generated
                        # region commits nothing until it catches up
                        self._trace(seq.seq_id, "decode",
                                    new_tokens=1 if caught_up else 0)
                touched[seq.seq_id] = self._outputs[seq.seq_id]
            self.metrics.decode_steps.inc()
            if step_prefill_rows:
                # the ragged step is ONE executable: a step serving any
                # number of prefill-chunk rows is ONE prefill launch
                self.metrics.prefill_launches.inc()
            self.metrics.ragged_pad_fraction.set(plan.pad_fraction)
        if self._tiered:
            # cursor-ahead prefetch: issue background staging for the
            # parked sequences the NEXT admission round will restore —
            # the staging thread gets a full step of compute to overlap
            for sid in self.scheduler.prefetch_candidates(
                    self._kv_prefetch_depth):
                self.pool.prefetch(sid)
            # tier events (stalls, host-chain promotions) surface on
            # the flight recorder (+ tracer span for request-owned
            # stalls) in the deterministic order the pool recorded them
            for kind, detail in self.pool.drain_events():
                self.flight.record(kind, self._now(), **detail)
                rid = detail.get("request")
                if rid is not None:
                    self._trace(rid, kind,
                                **{k: v for k, v in detail.items()
                                   if k != "request"})
        if self.tenant_policy is not None:
            # cost attribution on the engine's own clock: KV byte-seconds
            # for resident pages and adapter-slot residency seconds accrue
            # against the owning tenant's ledger every step
            now = self._now()
            dt = (now - self._last_cost_t) \
                if self._last_cost_t is not None else 0.0
            self._last_cost_t = now
            if dt > 0:
                bpt = self.pool.kv_bytes_per_token
                for seq in self.scheduler.running:
                    self.tenant_policy.charge_kv(
                        seq.tenant_id, seq.cached_len * bpt * dt)
                    if seq.adapter_slot:
                        self.tenant_policy.charge_slot(seq.tenant_id, dt)
        self.metrics.record_step(self.scheduler, self.pool)
        # one O(1) flight-recorder entry per step: the bounded last-N
        # context a post-mortem dump replays (ints only — cheap and
        # deterministic)
        f = {"running": len(self.scheduler.running),
             "waiting": len(self.scheduler.waiting),
             "used_pages": self.pool.used_pages,
             "tokens": self.metrics.tokens_generated.value}
        if self.engine_id is not None:
            f["engine"] = self.engine_id
        self.flight.record("step", self._now(), **f)
        return list(touched.values())

    def run(self, max_steps=None):
        """Drive step() until every request resolves; returns outputs."""
        steps = 0
        while self.has_unfinished():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps")
        return self.outputs()

    # ------------------------------------------------------------------
    # prefix cache
    # ------------------------------------------------------------------
    def _register_prefix(self, seq: Sequence):
        """Index the sequence's prompt as a fork donor: one entry per
        page-aligned prefix plus the full prompt (the identical-prompt
        fast path, which shares even the partial tail page). Newest
        registration wins, so a chain stays alive as long as ANY sharer
        of its pages is — entries whose donor left the pool fail the
        probe's liveness re-validation and are simply re-prefilled. The
        map is LRU-bounded (``prefix_cache_size``): re-registration
        refreshes recency, the oldest entries fall off — a long-running
        server's cache footprint is capped, not proportional to every
        prompt ever served."""
        P = seq.prompt_ids
        ps = self.page_size
        for j in list(range(ps, len(P) + 1, ps)) + [len(P)]:
            key = tuple(P[:j])
            self._prefix_cache.pop(key, None)      # refresh LRU position
            self._prefix_cache[key] = (seq.seq_id, j)
        while len(self._prefix_cache) > self.prefix_cache_size:
            self._prefix_cache.pop(next(iter(self._prefix_cache)))
        # pinned-LRU registration: the FULL pages of the prompt prefix
        # get an rc floor in the pool, so the chain survives its last
        # sequence sharer (up to the pinned-page budget) and repeated
        # cold prompts re-fork instead of re-prefilling. Only full pages
        # pin: partial tail pages are append targets (and, int8, requant
        # targets) — they must die with their writers.
        full = (len(P) // ps) * ps
        if full >= ps and self.pool.pinned_page_budget > 0:
            chain = tuple(P[:full])
            if self.pool.pin(chain, seq.seq_id, full):
                for j in list(range(ps, full + 1, ps)):
                    key = tuple(P[:j])
                    self._pinned_index.pop(key, None)
                    self._pinned_index[key] = (chain, j)
                while len(self._pinned_index) > self.prefix_cache_size:
                    self._pinned_index.pop(next(iter(self._pinned_index)))
                if self.fleet_prefix is not None \
                        and not self.fleet_prefix.contains(chain):
                    # fleet publication: one device->host export per NEW
                    # chain (content-addressed — a chain already in the
                    # fleet index costs one dict probe). Any replica in
                    # either pool can now fault these pages in.
                    self.fleet_prefix.publish(
                        chain, full, self.pool.export_chain(chain),
                        self.pool.config(), page_size=ps)
            if self._prefix_autosave:
                # write-ahead warm-start discipline: the pin set changed
                # (or an eviction shifted it) — persist the chains NOW,
                # because a crash never schedules a save first.
                # save_prefix_store no-ops when membership is unchanged.
                self.save_prefix_store()

    # ---- persistent prefix store (io/persist.py) ----
    PREFIX_STORE_TAG = "prefix_store"

    def export_prefix_store(self):
        """Serialize the pool's pinned chains + the engine's token-chain
        index as an (arrays, meta) pair for
        :meth:`~paddle_tpu.io.persist.ArtifactStore.save`. Chain ids at
        the engine level ARE the token tuples, so the index restores
        content-addressed — no donor liveness to re-validate."""
        chains = self.pool.export_pinned()
        arrays = {}
        meta_chains = []
        for ci, ch in enumerate(chains):
            for li, ent in enumerate(ch["layers"]):
                for part, arr in ent.items():
                    arrays[f"c{ci}/L{li}/{part}"] = arr
            meta_chains.append({"tokens": [int(t) for t in ch["chain_id"]],
                                "num_tokens": int(ch["num_tokens"])})
        meta = {"format": 1, "config": self.pool.config(),
                "chains": meta_chains}
        return arrays, meta

    def save_prefix_store(self) -> bool:
        """Persist the current pinned-chain set (atomic, versioned,
        checksummed). No-op without a store or without pins changed
        since the last save. Counted on ``prefix_store_saves``.

        Cost: one device->host copy + npz write of EVERY pinned chain —
        O(pinned bytes), bounded by ``pinned_prefix_pages`` (pin churn
        amortizes through the membership-signature dedup). Deployments
        with large pin budgets under heavy churn should construct with
        ``prefix_store_autosave=False`` and call this explicitly at
        drain/idle boundaries instead."""
        if self.prefix_store is None:
            return False
        sig = frozenset(self.pool._pins) \
            | frozenset(getattr(self.pool, "_host_chains", ()))
        if sig == self._prefix_store_sig:
            return False
        arrays, meta = self.export_prefix_store()
        self.prefix_store.save(self.PREFIX_STORE_TAG, arrays, meta)
        self._prefix_store_sig = sig
        self.metrics.prefix_store_saves.inc()
        return True

    # ------------------------------------------------------------------
    # adapter registry (tenancy/adapters.py)
    # ------------------------------------------------------------------
    def add_adapter(self, adapter_id, arrays) -> int:
        """Hot-publish a LoRA adapter into the serving slab — an in-place
        ``.at[slot].set`` on the stacked factors, so slab SHAPES never
        change and the ragged executable never retraces. Returns the
        slot. Re-publishing an id updates it in place (new requests see
        the new factors; in-flight rows keep decoding on the slab they
        were launched with)."""
        if self.adapters is None:
            raise ValueError(
                "engine was built without adapter_slots; construct with "
                "adapter_slots=N to serve LoRA adapters")
        slot = self.adapters.add(adapter_id, arrays)
        self.flight.record("adapter_add", self._now(),
                           adapter=str(adapter_id), slot=slot)
        if self._adapter_autosave:
            self.save_adapters()
        return slot

    def evict_adapter(self, adapter_id):
        """Drop an adapter from the slab (slot zeroes back to the base
        identity). Refuses with :class:`~paddle_tpu.tenancy.adapters.
        AdapterInUse` while any in-flight request references it."""
        if self.adapters is None:
            raise ValueError("engine has no adapter registry")
        self.adapters.evict(adapter_id)
        self.flight.record("adapter_evict", self._now(),
                           adapter=str(adapter_id))
        if self._adapter_autosave:
            self.save_adapters()

    def save_adapters(self) -> bool:
        """Persist the adapter slab (atomic, versioned, checksummed via
        io/persist.py). No-op without a store or without publishes since
        the last save. Counted on ``adapter_store_saves``."""
        if self.adapter_store is None or self.adapters is None \
                or not self.adapters.dirty:
            return False
        if self.adapters.save(self.adapter_store) is None:
            return False
        self.metrics.adapter_store_saves.inc()
        return True

    def _restore_prefix_store(self):
        """Warm-reload pinned chains at construction. Failure ladder:
        geometry/dtype drift raises :class:`PrefixStoreMismatch`
        (operator error); everything else — no store yet, every version
        corrupt, a chain that no longer fits the budget — degrades to a
        cold start with the ``restore_fallbacks`` counter and a flight-
        recorder event. Silent wrong KV bytes are impossible: data
        arrives checksum-verified or not at all."""
        store = self.prefix_store
        tag = self.PREFIX_STORE_TAG
        res = store.load(tag)
        if res is None:
            if store.versions(tag):
                # versions exist but none verified: a real loss, not a
                # first boot — count it and leave a post-mortem trail
                self.metrics.restore_fallbacks.inc()
                self.record_fleet_event(
                    "prefix_restore_fallback", reason="all_corrupt",
                    versions=len(store.versions(tag)))
            return
        if res.fallbacks:
            # a newer version was torn/corrupt and an older one served:
            # the warm start still happens, but the loss is visible
            self.metrics.restore_fallbacks.inc(res.fallbacks)
            self.record_fleet_event(
                "prefix_restore_fallback", reason="stale_version",
                served_version=res.version, skipped=res.fallbacks)
        live = self.pool.config()
        stored = dict(res.meta.get("config", {}))
        if stored != live:
            raise PrefixStoreMismatch(live, stored)
        restored = 0
        for ci, ch in enumerate(res.meta.get("chains", [])):
            tokens = tuple(int(t) for t in ch["tokens"])
            n = int(ch["num_tokens"])
            layers = []
            try:
                for li in range(self.pool.num_layers):
                    ent = {"K": res.arrays[f"c{ci}/L{li}/K"],
                           "V": res.arrays[f"c{ci}/L{li}/V"]}
                    if self.pool.quantized:
                        ent["Ks"] = res.arrays[f"c{ci}/L{li}/Ks"]
                        ent["Vs"] = res.arrays[f"c{ci}/L{li}/Vs"]
                    layers.append(ent)
            except KeyError:
                # manifest verified, so a missing leaf means the chain
                # was saved under a different pool mode (fp chains into
                # an int8 pool slips past the config gate only when the
                # configs were hand-edited) — skip it, count it
                self.metrics.restore_fallbacks.inc()
                continue
            try:
                ok = self.pool.restore_pinned_chain(tokens, n, layers)
            except ValueError as e:
                raise PrefixStoreMismatch(
                    live, dict(stored, chain_error=str(e)))
            if not ok:
                continue                 # over budget: cache, not demand
            ps = self.page_size
            for j in range(ps, n + 1, ps):
                key = tokens[:j]
                self._pinned_index.pop(key, None)
                self._pinned_index[key] = (tokens, j)
            restored += 1
        while len(self._pinned_index) > self.prefix_cache_size:
            self._pinned_index.pop(next(iter(self._pinned_index)))
        if restored:
            self.metrics.prefix_chains_restored.inc(restored)
            self.record_fleet_event("prefix_restore", chains=restored,
                                    version=res.version)
        # membership signature spans BOTH tiers: a host-tier chain
        # promoting to HBM (or being added/evicted) must re-arm the
        # autosave dedup like any pin-set change
        self._prefix_store_sig = frozenset(self.pool._pins) \
            | frozenset(getattr(self.pool, "_host_chains", ()))

    def _prefix_probe(self, seq: Sequence) -> int:
        """Admission hook: longest registered chain matching the prompt
        -> fork the donor's pages. Returns the shared (committed) token
        count, 0 on miss. The last prompt token is never shared — its
        logits must be computed to sample the first generated token — so
        an identical prompt re-runs exactly one token, whose append
        copy-on-writes the shared tail page."""
        P = seq.prompt_ids
        ps = self.page_size
        cands = sorted({len(P)} | set(range(ps, len(P) + 1, ps)),
                       reverse=True)
        for j in cands:
            ent = self._prefix_cache.get(tuple(P[:j]))
            if ent is None:
                continue
            donor, length = ent
            if donor == seq.seq_id or donor not in self.pool:
                continue
            if self._tiered and not self.pool.fully_resident(donor):
                # a parked donor's prefix may be spilled: forking would
                # map host sentinels into the child — skip (the pinned
                # index below may still serve the chain)
                continue
            if self.pool.seq_len(donor) < length:
                continue
            # a request_id can be reused after release(): the entry's
            # donor id may now name a DIFFERENT prompt's pages, so the
            # chain must be re-validated against the donor's actual
            # prompt tokens, not just its liveness
            donor_seq = self._seqs.get(donor)
            if donor_seq is None or \
                    donor_seq.prompt_ids[:j] != P[:j]:
                continue
            shared = min(j, len(P) - 1)
            if self.pool.quantized:
                # int8 pages requantize in place on append; only FULL
                # (append-free) pages are safe to share without a copy
                shared = (shared // ps) * ps
            if shared < 1:
                continue
            self.pool.fork(seq.seq_id, donor, num_tokens=shared)
            self.metrics.prefix_cache_hits.inc()
            return shared
        # no LIVE donor: fall back to the pinned-LRU chains — a prefix
        # whose last sharer already left the pool can still be forked
        # as long as its pin survived (budget LRU / pressure eviction)
        for j in cands:
            ent = self._pinned_index.get(tuple(P[:j]))
            if ent is None:
                continue
            chain, length = ent
            if not self.pool.is_pinned(chain):
                self._pinned_index.pop(tuple(P[:j]), None)   # evicted
                continue
            # pinned chains are full pages, registered under their exact
            # token tuple — content revalidation is the key itself. The
            # last prompt token is never shared (its logits seed the
            # first generated token); int8 full-page-only is automatic.
            shared = min(j, len(P) - 1)
            if self.pool.quantized:
                shared = (shared // ps) * ps
            if shared < 1:
                continue
            try:
                self.pool.fork_pinned(seq.seq_id, chain, shared)
            except PoolExhausted:
                # a HOST-tier chain (two-tier warm restart) could not
                # promote into HBM right now — treat as a miss rather
                # than killing admission; it stays restorable later
                continue
            self.metrics.prefix_cache_hits.inc()
            self.metrics.pinned_prefix_hits.inc()
            return shared
        # no local donor and no local pin: the FLEET prefix cache — a
        # chain some other replica published lands here through the
        # same two-tier restore + fork machinery the warm-restart store
        # uses. Store-backed bytes are checksum-verified; a geometry
        # mismatch is a counted miss, never a wrong-shape fork.
        if self.fleet_prefix is not None and self.pool.pinned_page_budget:
            for j in cands:
                if j % ps:
                    continue           # fleet chains are full pages only
                hit = self.fleet_prefix.lookup(tuple(P[:j]),
                                               self.pool.config())
                if hit is None:
                    continue
                chain, length, layers = hit
                if not self.pool.is_pinned(chain):
                    if not self.pool.restore_pinned_chain(
                            chain, length, layers):
                        continue       # over pin budget: cache, not demand
                shared = min(j, len(P) - 1)
                if self.pool.quantized:
                    shared = (shared // ps) * ps
                if shared < 1:
                    continue
                try:
                    self.pool.fork_pinned(seq.seq_id, chain, shared)
                except PoolExhausted:
                    continue
                for k in range(ps, length + 1, ps):
                    key = chain[:k]
                    self._pinned_index.pop(key, None)
                    self._pinned_index[key] = (chain, k)
                while len(self._pinned_index) > self.prefix_cache_size:
                    self._pinned_index.pop(next(iter(self._pinned_index)))
                self.metrics.prefix_cache_hits.inc()
                self.metrics.fleet_prefix_hits.inc()
                return shared
        self.metrics.prefix_cache_misses.inc()
        return 0

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _launch(self, plan, draft_tokens=None, draft_probs=None):
        """Assemble the fixed-shape operands for the plan and run the one
        ragged-step executable. Returns ``(out [R, K+1], n_out [R],
        finite [R])`` — ordinary rounds commit ``out[i, 0]`` (n_out is
        1), speculative rounds commit ``out[i, :n_out[i]]``; a row with
        ``finite[i] == False`` produced NaN/Inf logits and must be
        aborted instead of committed (the in-graph isfinite guard)."""
        T, R, PPS = plan.token_budget, plan.num_slots, self.max_pages_per_seq
        K = self.spec_tokens
        self.metrics.host_dispatches.inc()
        if not self._step_launched:
            self._step_launched = True
            self.metrics.decode_compiles.inc()
        tokens = np.zeros((T,), np.int32)
        positions = np.zeros((T,), np.int32)
        tbls = np.full((R, PPS), NULL_PAGE, np.int32)
        q_starts = np.full((R,), T, np.int32)   # pad rows: start past T
        q_lens = np.zeros((R,), np.int32)
        kv_lens = np.zeros((R,), np.int32)
        sample_idx = np.zeros((R, K + 1), np.int32)
        temps = np.zeros((R,), np.float32)
        top_ks = np.zeros((R,), np.int32)
        top_ps = np.ones((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        sample_pos = np.zeros((R,), np.int32)
        spec_lens = np.zeros((R,), np.int32)
        slot_ids = np.zeros((T,), np.int32) \
            if self.adapters is not None else None
        if draft_tokens is None:
            # ordinary round: the prebuilt zero operands (never indexed
            # below — every row has spec == 0)
            draft_tokens, draft_probs = self._zero_draft
        specs = plan.spec_lens
        for i, (seq, q_start, q_len) in enumerate(plan.rows):
            ids = seq.all_ids
            lo = seq.cached_len
            spec = specs[i] if specs is not None else 0
            if spec > 0:
                # verification chunk: the row's one uncached token plus
                # its draft candidates (not part of all_ids yet)
                row_toks = [ids[lo]] + [int(t) for t in
                                        draft_tokens[i, :spec]]
            else:
                row_toks = ids[lo:lo + q_len]
            tokens[q_start:q_start + q_len] = row_toks
            positions[q_start:q_start + q_len] = np.arange(lo, lo + q_len)
            tbls[i] = self.pool.padded_block_table(seq.seq_id, PPS)
            q_starts[i] = q_start
            q_lens[i] = q_len
            kv_lens[i] = lo + q_len
            last = q_start + q_len - 1
            sample_idx[i] = np.clip(last - spec + np.arange(K + 1),
                                    0, last)
            temps[i] = seq.temperature
            top_ks[i] = seq.top_k or 0
            top_ps[i] = 1.0 if seq.top_p is None else seq.top_p
            seeds[i] = seq.seed
            sample_pos[i] = len(seq.tokens)
            spec_lens[i] = spec
            if slot_ids is not None and seq.adapter_slot:
                slot_ids[q_start:q_start + q_len] = seq.adapter_slot
        out, n_out, finite, new_kv, new_scales = self._ragged_jit(
            self._ragged_params, self.pool.kv, self.pool.kv_scales,
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(tbls),
            jnp.asarray(q_starts), jnp.asarray(q_lens),
            jnp.asarray(kv_lens), jnp.asarray(sample_idx),
            jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
            jnp.asarray(seeds), jnp.asarray(sample_pos),
            jnp.asarray(spec_lens), jnp.asarray(draft_tokens),
            jnp.asarray(draft_probs), self._base_key,
            self.adapters.slab if self.adapters is not None else None,
            jnp.asarray(slot_ids) if slot_ids is not None else None)
        self.pool.kv = new_kv
        if new_scales is not None:
            self.pool.kv_scales = new_scales
        return np.asarray(out), np.asarray(n_out), np.asarray(finite)

    def _launch_spec(self, plan, touched):
        """One speculative round: draft sync + k proposal steps, then
        ONE target launch verifying every row's k+1 positions through
        the ordinary ragged executable. Accepted tokens commit through
        the normal path (streaming, eos/length finalization); the
        rejected tail rolls the pool's committed length back WITHOUT
        freeing pages (the slots are garbage the next append
        overwrites), and the draft pool rolls back the same way."""
        K = self.spec_tokens
        R = self.max_num_seqs
        seqs = [seq for seq, _, _ in plan.rows]
        spec_lens = plan.spec_lens
        try:
            self._draft.sync(seqs)
            d_toks, d_probs = self._draft.propose(seqs, spec_lens, K)
        except PoolExhausted:
            # the draft pool cannot hold this round: forget every draft
            # allocation (they re-sync from scratch when pressure
            # clears), roll the target pool's speculative page claims
            # back to the committed lengths (pages stay owned), and
            # tell step() to run an ordinary round instead
            for s in seqs:
                self._draft.drop(s.seq_id)
            for seq, _, _ in plan.rows:
                self.pool.rollback(seq.seq_id, seq.cached_len)
            self.metrics.spec_draft_fallbacks.inc()
            return False
        # d_toks are host-side (the verifier packs them into its query
        # buffer); d_probs is already the [R, K, V] DEVICE operand
        draft_tokens = np.zeros((R, K), np.int32)
        draft_tokens[:len(seqs)] = d_toks
        out, n_out, finite = self._launch(plan, draft_tokens, d_probs)
        drafted = accepted = rollbacks = 0
        for i, (seq, _q_start, _q_len) in enumerate(plan.rows):
            if not finite[i]:
                self._abort_nonfinite(seq)
                touched[seq.seq_id] = self._outputs[seq.seq_id]
                continue
            spec = spec_lens[i]
            cached_old = seq.cached_len
            n = int(n_out[i])            # 1..spec+1 tokens to commit
            drafted += spec
            accepted += n - 1
            if n - 1 < spec:
                rollbacks += 1
            committed = 0
            for j in range(n):
                committed += 1
                self._commit_token(seq, int(out[i, j]))
                if seq.status is not SequenceStatus.RUNNING:
                    break                # eos/length finalized mid-chain
            if seq.status is SequenceStatus.RUNNING:
                seq.cached_len = cached_old + committed
                self.pool.rollback(seq.seq_id, seq.cached_len)
                self._draft.commit(seq.seq_id, cached_old,
                                   committed - 1, spec)
            self._trace(seq.seq_id, "spec_round", drafted=int(spec),
                        accepted=int(n - 1), new_tokens=int(committed),
                        rollback=bool(n - 1 < spec))
            touched[seq.seq_id] = self._outputs[seq.seq_id]
        m = self.metrics
        m.spec_rounds.inc()
        if drafted:
            m.spec_drafted_tokens.inc(drafted)
        if accepted:
            m.spec_accepted_tokens.inc(accepted)
        if rollbacks:
            m.spec_rollbacks.inc(rollbacks)
        if m.spec_drafted_tokens.value:
            m.spec_accept_rate.set(m.spec_accepted_tokens.value
                                   / m.spec_drafted_tokens.value)
        return True

    def _launch_burst(self, bplan, touched):
        """Assemble the fixed-shape burst operands and run the
        on-device token loop: ONE host dispatch for up to
        ``burst_tokens`` tokens per row. The host then replays the
        returned token buffer through the normal commit path (stream
        callbacks, EOS/length finalization, prefix registration) and
        re-syncs the pool's committed lengths."""
        R, PPS = self.max_num_seqs, self.max_pages_per_seq
        tokens = np.zeros((R,), np.int32)
        kv_lens = np.zeros((R,), np.int32)
        tbls = np.full((R, PPS), NULL_PAGE, np.int32)
        live = np.zeros((R,), bool)
        caps = np.zeros((R,), np.int32)
        temps = np.zeros((R,), np.float32)
        top_ks = np.zeros((R,), np.int32)
        top_ps = np.ones((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        gpos = np.zeros((R,), np.int32)
        eos_ids = np.full((R,), -1, np.int32)
        for i, (seq, cap) in enumerate(bplan.rows):
            tokens[i] = seq.all_ids[-1]
            kv_lens[i] = seq.cached_len
            tbls[i] = self.pool.padded_block_table(seq.seq_id, PPS)
            live[i] = True
            caps[i] = cap
            temps[i] = seq.temperature
            top_ks[i] = seq.top_k or 0
            top_ps[i] = 1.0 if seq.top_p is None else seq.top_p
            seeds[i] = seq.seed
            gpos[i] = len(seq.tokens)
            if seq.eos_token_id is not None:
                eos_ids[i] = seq.eos_token_id
        self.metrics.host_dispatches.inc()
        self.metrics.burst_launches.inc()
        if not self._burst_launched:
            # the burst loop is a second step executable: its compile
            # rides the same forensics counter as the ragged step's
            self._burst_launched = True
            self.metrics.decode_compiles.inc()
        out, gen, ok, new_kv, new_scales = self._burst_jit(
            self._step_params, self.pool.kv, self.pool.kv_scales,
            jnp.asarray(tokens), jnp.asarray(kv_lens), jnp.asarray(tbls),
            jnp.asarray(live), jnp.asarray(caps), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps), jnp.asarray(seeds),
            jnp.asarray(gpos), jnp.asarray(eos_ids),
            jnp.asarray(bplan.burst_len, jnp.int32), self._base_key)
        self.pool.kv = new_kv
        if new_scales is not None:
            self.pool.kv_scales = new_scales
        out = np.asarray(out)
        gen = np.asarray(gen)
        ok = np.asarray(ok)
        for i, (seq, cap) in enumerate(bplan.rows):
            if not ok[i]:
                # the row went non-finite at some loop iteration: every
                # token of this burst is suspect — commit none, roll the
                # pool's committed length back to the pre-burst state,
                # and abort with the structured error
                self.pool.set_seq_len(seq.seq_id, seq.cached_len)
                self._abort_nonfinite(seq)
                touched[seq.seq_id] = self._outputs[seq.seq_id]
                continue
            g = int(gen[i])
            seq.cached_len += g
            # prepare_burst committed cached + cap up front; shrink the
            # pool's committed length back to what the burst actually
            # appended (a row that finished mid-burst appended fewer)
            self.pool.set_seq_len(seq.seq_id, seq.cached_len)
            for j in range(g):
                self._commit_token(seq, int(out[i, j]))
            self._trace(seq.seq_id, "burst", new_tokens=g,
                        burst_cap=int(cap))
            touched[seq.seq_id] = self._outputs[seq.seq_id]

    def _commit_token(self, seq: Sequence, tok: int):
        seq.tokens.append(int(tok))
        first = seq.first_token_at is None
        if first:
            # TTFT numerator. Burst mode commits a whole burst at one
            # host boundary, so a burst's tokens share this timestamp —
            # latency quantizes to burst length by design (docs/BENCH.md)
            seq.first_token_at = self._now()
        self.metrics.tokens_generated.inc()
        if self.tenant_policy is not None:
            self.tenant_policy.charge_tokens(seq.tenant_id, 1)
            if first:
                self.tenant_policy.record_ttft(
                    seq.tenant_id, seq.first_token_at - seq.arrival)
        out = self._sync_output(seq)
        if seq.eos_token_id is not None and tok == seq.eos_token_id:
            self._finalize(seq, "finished", reason="eos")
        elif len(seq.tokens) >= seq.max_new_tokens:
            self._finalize(seq, "finished", reason="length")
        elif self._stream_cb is not None:
            self._stream_cb(seq.seq_id, int(tok), False)
        return out

    def _abort_nonfinite(self, seq: Sequence):
        """Structured abort for a row the in-graph isfinite guard
        flagged: the request finalizes with ``finish_reason
        "nonfinite_logits"`` (status aborted), its pages are freed, and
        the ``nonfinite_rows`` counter records the event — the engine
        keeps serving every other row instead of streaming garbage.
        The flight recorder auto-dumps its last-N context (the steps
        LEADING INTO the numeric blow-up are the post-mortem)."""
        self.metrics.nonfinite_rows.inc()
        self.flight.record("nonfinite", self._now(), request=seq.seq_id)
        self.flight_dump("nonfinite_logits", request=seq.seq_id)
        self._finalize(seq, "aborted", reason="nonfinite_logits")

    def _finalize(self, seq: Sequence, status: str, reason=None):
        if self._draft is not None:
            self._draft.drop(seq.seq_id)
        if self.adapters is not None and seq.adapter_id not in (0, None):
            self.adapters.release(seq.adapter_id)
            seq.adapter_id = 0        # idempotent across double-finalize
        self.scheduler.finish(seq, {
            "finished": SequenceStatus.FINISHED,
            "shed": SequenceStatus.SHED,
            "cancelled": SequenceStatus.CANCELLED,
            "aborted": SequenceStatus.ABORTED,
        }[status])
        out = self._sync_output(seq)
        out.finish_reason = reason or status
        if self.tracer is not None:
            # terminal span: kind encodes the lifecycle exit so the
            # breakdown/post-mortem can branch without string-matching
            # reasons (deadline_abort/nonfinite_abort/shed/finish)
            if reason == "deadline_exceeded":
                kind = "deadline_abort"
            elif reason == "nonfinite_logits":
                kind = "nonfinite_abort"
            elif status == "shed":
                kind = "shed"
            else:
                kind = "finish"
            # tenant attribution rides the span ONLY when set — classic
            # (no-tenant) traces stay byte-identical per seed
            extra = {} if seq.tenant_id is None \
                else {"tenant": seq.tenant_id}
            self._trace(seq.seq_id, kind, status=status,
                        reason=out.finish_reason,
                        tokens=len(seq.tokens), **extra)
        if status in ("shed", "aborted"):
            extra = {} if seq.tenant_id is None \
                else {"tenant": seq.tenant_id}
            self.flight.record(status, self._now(), request=seq.seq_id,
                               reason=out.finish_reason, **extra)
        if status == "finished":
            self.metrics.finished_requests.inc()
            self.metrics.record_request_end(
                arrival=seq.arrival, first_token_at=seq.first_token_at,
                finished_at=self._now(), n_tokens=len(seq.tokens))
            if self.tenant_policy is not None:
                self.tenant_policy.count_finished(seq.tenant_id)
        if self._stream_cb is not None:
            last = seq.tokens[-1] if seq.tokens else None
            self._stream_cb(seq.seq_id, last, True)
        return out

    def _sync_output(self, seq: Sequence) -> RequestOutput:
        out = self._outputs[seq.seq_id]
        out.token_ids = list(seq.tokens)
        out.status = seq.status.value
        out.num_preemptions = seq.num_preemptions
        out.tenant_id = seq.tenant_id
        return out


__all__ = ["LLMEngine", "Request", "RequestOutput", "RequestRejected"]
