"""Paged KV-pool manager — the allocator under the serving engine.

The Pallas ragged kernel (kernels/paged_attention.py) consumes a paged
pool ``[num_kv_heads, num_pages, page_size, head_dim]`` plus per-sequence
block tables; this module owns the layer above it: which pool page
belongs to which live sequence, and what happens when the pool runs dry.
It is the TPU analog of vLLM's BlockSpaceManager and of the reference's
block_multi_head_attention cache manager:

- a free-list allocator over pool pages — page granularity means there is
  no external fragmentation by construction: any request for n free pages
  succeeds iff n pages are free;
- per-sequence block tables (logical page i of a sequence -> pool page),
  grown one page at a time as decode/prefill-chunks cross page boundaries;
- pool page 0 is reserved as the NULL page: padded rows and padded
  block-table slots all point at it, so fixed-shape ragged launches have
  a safe write/read target that never aliases live data;
- **copy-on-write page sharing**: every mapped page carries a refcount.
  ``fork(child, parent, num_tokens)`` maps the parent's pages covering a
  shared prompt prefix into the child's table (refcount + 1, zero data
  movement) — identical system prompts across millions of users occupy
  ONE set of pool pages. A page is copied only when an owner is about to
  APPEND into a page someone else also maps (``prepare_append``): full
  prefix pages are append-free and therefore shared forever; only a
  partially-filled tail page is ever duplicated, right before the first
  divergent append. ``free`` decrements refcounts and recycles a page
  only when the last owner drops it;
- utilization watermarks the scheduler uses for admission control and
  preemption decisions.

Low-bit pools (``dtype=jnp.int8``): K/V pages are stored int8 with one
fp32 scale per (kv head, page) — ``kv_scales``, one (Ks, Vs) pair per
layer, shape [num_kv_heads, num_pages]. The engine quantizes on append
and the ragged kernel dequantizes at the gather (scales ride the
scalar-prefetch channel into SMEM). Shared pages interact with the
scales safely only because shared pages are never appended into without
a CoW copy first: an append can requantize the whole page in place
(running-amax scale growth), which would perturb every other reader —
so the engine restricts int8 prefix sharing to FULL pages, which are
append-free, and ``cow_page`` copies the page's scale row with its data.

The device arrays themselves live in ``kv`` (one (K, V) pair per layer)
and are updated *functionally* by the engine's jitted ragged step (the
engine reassigns ``kv`` after each donated call); this class tracks the
host-side ownership metadata plus the eager CoW/scale-reset fixups.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _copy_pages(kv, old_idx, new_idx):
    """Duplicate pool pages ``old_idx`` into ``new_idx`` across every
    layer's (K, V) pair — the device side of copy-on-write."""
    return [(K.at[:, new_idx].set(K[:, old_idx]),
             V.at[:, new_idx].set(V[:, old_idx])) for K, V in kv]


_COPY_JIT = None


def _copy_pages_jit(kv, old_idx, new_idx):
    """One jitted (donated on TPU) scatter per CoW batch: only the
    affected page slices move, instead of a full functional copy of the
    pool per page per layer. Re-traces per distinct batch size — CoW
    batches are almost always 1 page."""
    global _COPY_JIT
    if _COPY_JIT is None:
        from ..kernels import _on_tpu
        donate = (0,) if _on_tpu() else ()
        _COPY_JIT = jax.jit(_copy_pages, donate_argnums=donate)
    return _COPY_JIT(kv, old_idx, new_idx)


class PoolExhausted(RuntimeError):
    """Raised when an alloc/extend/CoW needs more free pages than exist."""


class InvariantViolation(AssertionError):
    """Structured ``check_invariants`` failure: the reason plus a pool
    snapshot (refcounts, free-list size, pinned set, offending page ids)
    travel with the exception, so a loadgen soak that dies hundreds of
    virtual steps in is triageable from the artifact alone instead of a
    bare assert with no state. Subclasses ``AssertionError`` so callers
    (and tests) that caught the old asserts keep working."""

    #: flight-recorder post-mortem (the last-N engine/fleet events
    #: leading into the failure) when the pool had a recorder attached
    #: (serving/tracing.py) — None for bare pools
    flight_dump = None

    def __init__(self, reason, snapshot):
        self.reason = reason
        self.snapshot = snapshot
        rcs = snapshot["refcounts"]
        head = dict(list(rcs.items())[:16])
        super().__init__(
            f"{reason} | pool snapshot: used={snapshot['used_pages']}/"
            f"{snapshot['capacity']} free_list={snapshot['free_list_size']} "
            f"offending_pages={snapshot['offending_pages']} "
            f"pinned_chains={len(snapshot['pinned'])} "
            f"nonzero_refcounts={head}"
            f"{'...' if len(rcs) > 16 else ''}")


NULL_PAGE = 0


class PagedKVPool:
    """Refcounted free-list page allocator + per-sequence block tables.

    capacity = ``num_pages - 1`` allocatable pages (page 0 is the null
    page). ``seq_lens`` tracks the token count the engine has committed
    per sequence, so ``pages_needed`` and utilization stay in one place.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 page_size, dtype=jnp.float32, high_watermark=0.90,
                 low_watermark=0.50, pinned_page_budget=0, mesh=None):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        # tensor-parallel pool: pages (and int8 scale rows) shard over
        # the mesh's model axis on dim 0 — the kv-head axis — so each
        # device holds Hkv/tp heads' pages. The jitted ragged step's
        # sharding inference keeps the updated pool on the same axis,
        # so the split survives across steps without re-placement.
        self.mesh = mesh
        if mesh is not None:
            from ..distributed.gspmd import MODEL_AXIS
            tp = mesh.shape.get(MODEL_AXIS, 1)
            if num_kv_heads % tp:
                raise ValueError(
                    f"PagedKVPool(mesh=...): {num_kv_heads} kv heads do "
                    f"not divide over the {tp}-way model axis")
        if not 0.0 < low_watermark <= high_watermark <= 1.0:
            raise ValueError("watermarks must satisfy 0 < low <= high <= 1")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.dtype = jnp.dtype(dtype)
        self.quantized = self.dtype == jnp.dtype(jnp.int8)
        shape = (num_kv_heads, num_pages, page_size, head_dim)
        self.kv = [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                   for _ in range(num_layers)]
        # per-(head, page) dequant scales for int8 pools; zero-init so a
        # fresh page's first append sets the scale from its own amax
        # instead of inheriting a fabricated range
        self.kv_scales = None
        if self.quantized:
            sshape = (num_kv_heads, num_pages)
            self.kv_scales = [(jnp.zeros(sshape, jnp.float32),
                               jnp.zeros(sshape, jnp.float32))
                              for _ in range(num_layers)]
        self._repin()   # initial mesh placement (no-op without a mesh)
        # LIFO free list: recently-freed pages are reused first (warm in
        # whatever cache level holds them)
        self._free = list(range(num_pages - 1, NULL_PAGE, -1))
        self._tables: dict[object, list[int]] = {}
        self._lens: dict[object, int] = {}
        #: pool page -> number of owners mapping it: sequences AND pinned
        #: prefix chains both count (0 for free pages)
        self._refcounts = [0] * num_pages
        #: lifetime count of copy-on-write page duplications
        self.cow_copies = 0
        #: pinned prefix chains: chain_id -> (pages, num_tokens), in LRU
        #: order (dict preserves insertion; re-pin/touch re-appends). A
        #: pin is one extra refcount per page — the "rc floor" that lets
        #: a prefix chain outlive its last sequence sharer, up to
        #: ``pinned_page_budget`` pages (LRU-evicted beyond it, and
        #: auto-evicted whenever an allocation would otherwise exhaust
        #: the pool — pinned pages are cache, never demand).
        self.pinned_page_budget = int(pinned_page_budget)
        self._pins: dict[object, tuple[list[int], int]] = {}
        #: pool page -> number of pinned chains mapping it
        self._pin_counts: dict[int, int] = {}
        #: lifetime count of pinned chains evicted (budget or pressure)
        self.pin_evictions = 0

    # ---- byte accounting (pool sizing / bench fields) ----
    @staticmethod
    def page_bytes_for(num_layers, num_kv_heads, head_dim, page_size,
                       dtype=jnp.float32) -> int:
        """HBM bytes one pool page costs across all layers, K+V, scale
        rows included for int8 pools."""
        dt = jnp.dtype(dtype)
        data = num_layers * 2 * num_kv_heads * page_size * head_dim \
            * dt.itemsize
        scales = num_layers * 2 * num_kv_heads * 4 \
            if dt == jnp.dtype(jnp.int8) else 0
        return data + scales

    @classmethod
    def pages_for_byte_budget(cls, byte_budget, num_layers, num_kv_heads,
                              head_dim, page_size,
                              dtype=jnp.float32) -> int:
        """Largest ``num_pages`` whose pool fits ``byte_budget`` — how an
        operator sizes fp32 vs int8 pools at the same HBM watermark (the
        ~2x-sequences-per-byte win the int8 pool exists for)."""
        per = cls.page_bytes_for(num_layers, num_kv_heads, head_dim,
                                 page_size, dtype)
        return max(int(byte_budget) // per, 0)

    @property
    def page_bytes(self) -> int:
        return self.page_bytes_for(self.num_layers, self.num_kv_heads,
                                   self.head_dim, self.page_size,
                                   self.dtype)

    @property
    def kv_bytes_per_token(self) -> float:
        """Bytes of pool one cached token occupies (scale rows amortized
        over the page's tokens) — bench.py's ``kv_bytes_per_token``."""
        return self.page_bytes / self.page_size

    @property
    def pool_bytes(self) -> int:
        return self.page_bytes * self.num_pages

    @property
    def model_parallel_degree(self) -> int:
        """Ways the kv-head axis is split over a mesh's model axis."""
        if self.mesh is None:
            return 1
        from ..distributed.gspmd import MODEL_AXIS
        return self.mesh.shape.get(MODEL_AXIS, 1)

    @property
    def kv_bytes_per_token_per_device(self) -> float:
        """Pool bytes one cached token occupies PER DEVICE — the number
        that decides whether a model's KV traffic fits one chip's HBM
        (global bytes / model-parallel degree; the tensor-parallel
        serving win the sharded pool exists for)."""
        return self.kv_bytes_per_token / self.model_parallel_degree

    # ---- capacity ----
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    @property
    def utilization(self) -> float:
        return self.used_pages / self.capacity

    @property
    def logical_pages(self) -> int:
        """Block-table slots across live sequences — what the pool WOULD
        hold without sharing."""
        return sum(len(t) for t in self._tables.values())

    @property
    def shared_page_fraction(self) -> float:
        """Fraction of logical pages served by a shared physical page:
        ``1 - physical/logical``. 0.0 with no sharing; approaches
        ``(N-1)/N`` when N sequences share one long prefix — the
        admitted-sequences-per-byte win prefix caching exists for."""
        logical = self.logical_pages
        if logical == 0:
            return 0.0
        return 1.0 - self.used_pages / logical

    def page_refcount(self, page: int) -> int:
        return self._refcounts[page]

    def above_high_watermark(self, extra_pages=0) -> bool:
        # pinned-exclusive pages are reclaimable cache, not demand: a
        # pool full of evictable prefixes must not read as pressure (it
        # would pause admission with nothing left to drain it)
        demand = self.used_pages - self.evictable_pages
        return (demand + extra_pages) / self.capacity \
            > self.high_watermark

    def below_low_watermark(self) -> bool:
        demand = self.used_pages - self.evictable_pages
        return demand / self.capacity < self.low_watermark

    def pages_for(self, num_tokens: int) -> int:
        return -(-max(num_tokens, 0) // self.page_size)

    def can_allocate(self, num_tokens: int) -> bool:
        return self.pages_for(num_tokens) <= len(self._free)

    @property
    def pinned_pages(self) -> int:
        """Distinct pool pages held by at least one pinned chain."""
        return len(self._pin_counts)

    @property
    def evictable_pages(self) -> int:
        """Pinned pages whose ONLY owners are pins (no sequence maps
        them) — the pages unpinning would actually recycle."""
        return sum(1 for p, n in self._pin_counts.items()
                   if self._refcounts[p] == n)

    @property
    def available_pages(self) -> int:
        """Free pages plus reclaimable pinned-exclusive pages — what an
        admission decision should compare against (pinned prefixes are
        cache: they yield to demand via LRU eviction)."""
        return len(self._free) + self.evictable_pages

    def _repin(self):
        """Re-place the pool arrays on the mesh sharding after an EAGER
        fixup (CoW copy, recycled-page scale reset): eager ops choose
        their own output sharding, and a drifted placement would re-key
        the engine's jitted ragged step — one silent recompile per
        drift, exactly what the trace-count==1 gate forbids. device_put
        onto the sharding an array already has is free."""
        if self.mesh is None:
            return
        from ..distributed.gspmd import (kv_pool_sharding,
                                         kv_scale_sharding)
        psh = kv_pool_sharding(self.mesh)
        self.kv = [(jax.device_put(K, psh), jax.device_put(V, psh))
                   for K, V in self.kv]
        if self.kv_scales is not None:
            ssh = kv_scale_sharding(self.mesh)
            self.kv_scales = [(jax.device_put(Ks, ssh),
                               jax.device_put(Vs, ssh))
                              for Ks, Vs in self.kv_scales]

    # ---- lifecycle ----
    def _release_pages(self, pages) -> int:
        """Drop one refcount per page; recycle (free-list + int8 scale
        reset) the pages whose refcount hits zero. Returns the number of
        pages actually recycled."""
        recycled = []
        for p in reversed(list(pages)):
            self._refcounts[p] -= 1
            if self._refcounts[p] == 0:
                recycled.append(p)
        self._free.extend(recycled)
        if self.kv_scales is not None and recycled:
            # reset the recycled pages' dequant scales: the append
            # path's running max only ever GROWS a scale, so a recycled
            # page must not hand its next tenant the previous tenant's
            # (possibly much larger) range — that would quantize small
            # new values straight to zero. Pages still mapped elsewhere
            # keep their scales.
            idx = jnp.asarray(recycled, jnp.int32)
            self.kv_scales = [(Ks.at[:, idx].set(0.0),
                               Vs.at[:, idx].set(0.0))
                              for Ks, Vs in self.kv_scales]
            self._repin()
        return len(recycled)

    def _ensure_free(self, n: int, what: str):
        """Evict LRU pinned chains until ``n`` pages are free (or no
        eviction would recycle anything); raises
        :class:`PoolExhausted` on a real shortfall. Pinned prefixes are
        opportunistic cache — they must never turn real demand into an
        exhaustion the scheduler would answer with preemption — but a
        chain whose every page is also mapped by a live sequence frees
        nothing when unpinned, so those survive the shortfall (wiping
        them would cost the whole cache for zero pages)."""
        while n > len(self._free) and self._pins:
            victim = next(
                (cid for cid, (pages, _) in self._pins.items()
                 if any(self._refcounts[p] == self._pin_counts[p]
                        for p in pages)), None)
            if victim is None:
                break
            self.unpin(victim)
            self.pin_evictions += 1
        if n > len(self._free):
            raise PoolExhausted(
                f"{what}: need {n} pages, {len(self._free)} free of "
                f"{self.capacity}")

    def _claim(self, n: int, what: str) -> list[int]:
        self._ensure_free(n, what)
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refcounts[p] = 1
        return pages

    def allocate(self, seq_id, num_tokens: int) -> list[int]:
        """Claim pages for a new sequence of ``num_tokens`` tokens."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        pages = self._claim(self.pages_for(num_tokens),
                            f"allocate {num_tokens} tokens")
        self._tables[seq_id] = pages
        self._lens[seq_id] = num_tokens
        return pages

    def fork(self, seq_id, parent_id, num_tokens: int | None = None
             ) -> list[int]:
        """Map the parent's pages covering its first ``num_tokens``
        tokens (default: every FULL page of the parent's committed
        prefix) into a new sequence ``seq_id`` — zero data movement,
        refcount + 1 per shared page. The child starts with
        ``seq_len(seq_id) == num_tokens`` committed tokens; its first
        append into a partially-filled shared tail page triggers a
        copy-on-write duplication (``prepare_append``)."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        parent = self._tables[parent_id]
        if num_tokens is None:
            num_tokens = (self._lens[parent_id] // self.page_size) \
                * self.page_size
        if num_tokens > self._lens[parent_id]:
            raise ValueError(
                f"fork of {num_tokens} tokens exceeds parent "
                f"{parent_id!r}'s committed {self._lens[parent_id]}")
        shared = parent[:self.pages_for(num_tokens)]
        for p in shared:
            self._refcounts[p] += 1
        self._tables[seq_id] = list(shared)
        self._lens[seq_id] = num_tokens
        return list(shared)

    def extend(self, seq_id, new_len: int) -> list[int]:
        """Grow ``seq_id``'s table to cover ``new_len`` tokens; returns the
        newly claimed pages (possibly empty). All-or-nothing on exhaustion.
        """
        table = self._tables[seq_id]
        need = self.pages_for(new_len) - len(table)
        fresh = self._claim(max(need, 0),
                            f"extend {seq_id!r} to {new_len} tokens")
        table.extend(fresh)
        self._lens[seq_id] = max(new_len, self._lens[seq_id])
        return fresh

    def prepare_append(self, seq_id, new_len: int) -> int:
        """Make ``[seq_len, new_len)`` safely writable for ``seq_id``:
        claim fresh pages past the table's end AND copy-on-write every
        SHARED page the append range touches (a shared page may have
        other readers — dup it before the first divergent write).
        Commits ``seq_len = new_len``. All-or-nothing on exhaustion
        (fresh + CoW pages are counted up front). Returns the number of
        CoW copies performed (the metrics counter's increment)."""
        table = self._tables[seq_id]
        old_len = self._lens[seq_id]
        if new_len < old_len:
            raise ValueError(f"append cannot shrink {seq_id!r}: "
                             f"{old_len} -> {new_len}")
        need_fresh = max(self.pages_for(new_len) - len(table), 0)
        first = old_len // self.page_size
        last = self.pages_for(new_len)          # exclusive logical bound
        def _shared():
            return [i for i in range(first, min(last, len(table)))
                    if self._refcounts[table[i]] > 1]

        shared = _shared()
        # all-or-nothing: fresh + CoW pages are priced together, with
        # LRU pinned chains evicted first if that is what it takes
        self._ensure_free(
            need_fresh + len(shared),
            f"append {seq_id!r} to {new_len} tokens: need "
            f"{need_fresh} fresh + {len(shared)} CoW pages")
        # eviction may have dropped a pin's refcount on a page in the
        # write range — recompute so a now-exclusive page is written in
        # place instead of CoW'd into a leak
        shared = _shared()
        olds, news = [], []
        for i in shared:
            old = table[i]
            new = self._claim(1, f"CoW for {seq_id!r}")[0]
            self._refcounts[old] -= 1
            table[i] = new
            olds.append(old)
            news.append(new)
        if olds:
            # one batched device copy for the whole CoW set: page data
            # and (for int8 pools) the pages' scale columns travel
            # together — a duplicated page must dequantize identically
            old_idx = jnp.asarray(olds, jnp.int32)
            new_idx = jnp.asarray(news, jnp.int32)
            self.kv = _copy_pages_jit(self.kv, old_idx, new_idx)
            if self.kv_scales is not None:
                self.kv_scales = [
                    (Ks.at[:, new_idx].set(Ks[:, old_idx]),
                     Vs.at[:, new_idx].set(Vs[:, old_idx]))
                    for Ks, Vs in self.kv_scales]
            self._repin()
            self.cow_copies += len(olds)
        self.extend(seq_id, new_len)
        self._lens[seq_id] = new_len
        return len(olds)

    def free(self, seq_id) -> int:
        """Drop every page mapping the sequence owns; a page is recycled
        (returned to the free list) only when its refcount hits zero —
        pages a pinned prefix chain also holds survive at the pin's rc
        floor. Returns the number of pages actually recycled."""
        pages = self._tables.pop(seq_id)
        self._lens.pop(seq_id, None)
        return self._release_pages(pages)

    # ---- pinned prefix chains (LRU page cache over the pool) ----
    def pin(self, chain_id, seq_id, num_tokens: int) -> bool:
        """Pin the pages covering ``seq_id``'s first ``num_tokens``
        committed tokens (must be page-aligned: only FULL pages are
        append-free and therefore safe to outlive their writers) under
        ``chain_id``. The pin takes one refcount per page, so the chain
        survives the sequence's ``free`` — repeated cold prompts re-fork
        instead of re-prefilling. Re-pinning an existing chain refreshes
        its LRU recency. Returns False (and pins nothing) when the
        budget is 0 or the chain alone exceeds it."""
        if num_tokens % self.page_size != 0:
            raise ValueError(
                f"pinned chains must be page-aligned: {num_tokens} "
                f"tokens over page_size {self.page_size}")
        n_pages = num_tokens // self.page_size
        if n_pages < 1 or n_pages > self.pinned_page_budget:
            return False
        if self._lens.get(seq_id, -1) < num_tokens:
            raise ValueError(
                f"pin of {num_tokens} tokens exceeds {seq_id!r}'s "
                f"committed {self._lens.get(seq_id)}")
        if chain_id in self._pins:
            self.unpin(chain_id)                 # refresh (LRU + pages)
        pages = self._tables[seq_id][:n_pages]
        # LRU budget: evict oldest chains until this one fits
        while self.pinned_pages + n_pages > self.pinned_page_budget \
                and self._pins:
            self.unpin(next(iter(self._pins)))
            self.pin_evictions += 1
        for p in pages:
            self._refcounts[p] += 1
            self._pin_counts[p] = self._pin_counts.get(p, 0) + 1
        self._pins[chain_id] = (list(pages), num_tokens)
        return True

    def unpin(self, chain_id) -> int:
        """Drop a pinned chain's refcounts; recycles pages no sequence
        maps anymore. Returns the number of pages recycled."""
        pages, _ = self._pins.pop(chain_id)
        for p in pages:
            self._pin_counts[p] -= 1
            if self._pin_counts[p] == 0:
                del self._pin_counts[p]
        return self._release_pages(pages)

    def is_pinned(self, chain_id) -> bool:
        return chain_id in self._pins

    # ---- persistence (io/persist.py prefix store) ----
    def config(self) -> dict:
        """Geometry/dtype signature a persisted prefix chain must match
        to be restorable — the two sides of a restore-mismatch error."""
        return {"num_layers": self.num_layers,
                "num_kv_heads": self.num_kv_heads,
                "head_dim": self.head_dim,
                "page_size": self.page_size,
                "dtype": str(self.dtype)}

    def export_pinned(self) -> list:
        """Serialize every pinned chain's page data, LRU order (oldest
        first, so a restore under a smaller budget keeps the hottest
        chains last-written): per chain, per layer, the K/V page blocks
        ``[Hkv, n_pages, page_size, head_dim]`` (plus the per-(head,
        page) scale columns for int8 pools) as host numpy arrays."""
        out = []
        for cid, (pages, num_tokens) in self._pins.items():
            idx = jnp.asarray(pages, jnp.int32)
            layers = []
            for li, (K, V) in enumerate(self.kv):
                ent = {"K": np.asarray(K[:, idx]),
                       "V": np.asarray(V[:, idx])}
                if self.kv_scales is not None:
                    Ks, Vs = self.kv_scales[li]
                    ent["Ks"] = np.asarray(Ks[:, idx])
                    ent["Vs"] = np.asarray(Vs[:, idx])
                layers.append(ent)
            out.append({"chain_id": cid, "num_tokens": num_tokens,
                        "layers": layers})
        return out

    def export_chain(self, chain_id) -> list:
        """Serialize ONE pinned chain's page data (the per-chain slice
        of :meth:`export_pinned`) — what the fleet prefix cache
        (serving/fabric.py) publishes after a pin, without paying a
        device read of every other chain."""
        pages, _ = self._pins[chain_id]
        return self._read_pages(pages)

    def _read_pages(self, pages) -> list:
        """Device -> host read of pool pages as one
        ``[Hkv, len(pages), ps, d]`` block per layer (K/V + int8 scale
        columns) — the HostKVArena ``layers`` format, which makes spill
        buffers, fleet transfers, and prefix publishes one wire
        format."""
        idx = jnp.asarray(pages, jnp.int32)
        out = []
        for li, (K, V) in enumerate(self.kv):
            ent = {"K": np.asarray(K[:, idx]),
                   "V": np.asarray(V[:, idx])}
            if self.kv_scales is not None:
                Ks, Vs = self.kv_scales[li]
                ent["Ks"] = np.asarray(Ks[:, idx])
                ent["Vs"] = np.asarray(Vs[:, idx])
            out.append(ent)
        return out

    # ---- disaggregated serving (serving/fabric.py) ----
    def export_pages(self, seq_id, num_tokens=None) -> tuple:
        """Read the pages covering ``seq_id``'s first ``num_tokens``
        committed tokens (default: all of them) as host numpy blocks —
        the prefill side of a KV handoff. Returns ``(num_tokens,
        layers)`` in the arena/adopt wire format. Read-only: refcounts,
        tables, and sharing are untouched."""
        if num_tokens is None:
            num_tokens = self._lens[seq_id]
        if num_tokens > self._lens[seq_id]:
            raise ValueError(
                f"export of {num_tokens} tokens exceeds {seq_id!r}'s "
                f"committed {self._lens[seq_id]}")
        pages = self._tables[seq_id][:self.pages_for(num_tokens)]
        bad = [p for p in pages if p < 0]
        if bad:
            raise PoolExhausted(
                f"export of {seq_id!r}: {len(bad)} pages are not "
                f"HBM-resident (restore before extracting)")
        return num_tokens, self._read_pages(pages)

    def adopt_sequence(self, seq_id, num_tokens, layers) -> list:
        """Land transferred KV pages as a NEW fully-resident sequence —
        the decode side of a KV handoff (inverse of
        :meth:`export_pages`): claim fresh pages, write each layer's
        blocks (int8 scale columns included), and commit ``num_tokens``.
        All-or-nothing: :class:`PoolExhausted` when the pages cannot be
        claimed even after LRU pin eviction. The two-tier pool overrides
        this to stage into the host arena instead (the sequence lands
        PARKED and rides the prefetch/restore path into HBM)."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        if len(layers) != self.num_layers:
            raise ValueError(
                f"adopted sequence has {len(layers)} layers, pool has "
                f"{self.num_layers}")
        n_pages = self.pages_for(num_tokens)
        want = (self.num_kv_heads, n_pages, self.page_size, self.head_dim)
        for li, ent in enumerate(layers):
            if tuple(np.asarray(ent["K"]).shape) != want:
                raise ValueError(
                    f"adopted sequence layer {li}: block shape "
                    f"{tuple(np.asarray(ent['K']).shape)} != pool {want}")
        pages = self._claim(n_pages, f"adopt {seq_id!r} "
                                     f"({num_tokens} tokens)")
        idx = jnp.asarray(pages, jnp.int32)
        self.kv = [(K.at[:, idx].set(jnp.asarray(ent["K"], self.dtype)),
                    V.at[:, idx].set(jnp.asarray(ent["V"], self.dtype)))
                   for (K, V), ent in zip(self.kv, layers)]
        if self.kv_scales is not None:
            self.kv_scales = [
                (Ks.at[:, idx].set(jnp.asarray(ent["Ks"], jnp.float32)),
                 Vs.at[:, idx].set(jnp.asarray(ent["Vs"], jnp.float32)))
                for (Ks, Vs), ent in zip(self.kv_scales, layers)]
        self._repin()
        self._tables[seq_id] = list(pages)
        self._lens[seq_id] = num_tokens
        return list(pages)

    # single-tier pools have no host tier, so an adopted sequence is
    # already fully resident: the scheduler's parked-admission branch
    # (which fires for ANY sequence that owns a table while waiting)
    # sees zero spilled pages and a free no-op restore
    def spilled_page_count(self, seq_id) -> int:
        return 0

    def restore_headroom(self, seq_id) -> int:
        return self.available_pages

    def restore_sequence(self, seq_id) -> int:
        return 0

    def restore_pinned_chain(self, chain_id, num_tokens, layers) -> bool:
        """Materialize a persisted chain back into the pool as a pinned
        prefix: claim fresh pages, write each layer's K/V blocks (and
        int8 scale columns) into them, and register the pin — the warm-
        restart inverse of :meth:`export_pinned`. Returns False (and
        touches nothing) when the chain cannot fit (zero budget, chain
        alone over budget, or no free pages even after LRU eviction);
        raises ``ValueError`` on geometry violations (the engine wraps
        shape/dtype drift in its structured mismatch error before this
        layer ever sees it)."""
        if num_tokens % self.page_size != 0:
            raise ValueError(
                f"restored chains must be page-aligned: {num_tokens} "
                f"tokens over page_size {self.page_size}")
        n_pages = num_tokens // self.page_size
        if n_pages < 1 or n_pages > self.pinned_page_budget:
            return False
        if len(layers) != self.num_layers:
            raise ValueError(
                f"restored chain has {len(layers)} layers, pool has "
                f"{self.num_layers}")
        # feasibility BEFORE any mutation: eviction only ever recycles
        # pin-exclusive pages, so free + evictable bounds what a restore
        # can claim — deciding now keeps the touches-nothing contract
        # honest for post-init callers on a busy pool (evicting first
        # and then failing would have destroyed the warm cache for
        # nothing; at engine construction free pages alone suffice)
        if n_pages > len(self._free) + self.evictable_pages:
            return False
        if chain_id in self._pins:
            self.unpin(chain_id)
        while self.pinned_pages + n_pages > self.pinned_page_budget \
                and self._pins:
            self.unpin(next(iter(self._pins)))
            self.pin_evictions += 1
        # _claim's _ensure_free evicts further LRU chains if the budget
        # evictions freed too little; the upfront bound guarantees it
        # succeeds
        pages = self._claim(n_pages, f"restore pinned chain ({n_pages} "
                                     f"pages)")
        idx = jnp.asarray(pages, jnp.int32)
        new_kv = []
        for li, ((K, V), ent) in enumerate(zip(self.kv, layers)):
            k = jnp.asarray(ent["K"], self.dtype)
            v = jnp.asarray(ent["V"], self.dtype)
            want = (self.num_kv_heads, n_pages, self.page_size,
                    self.head_dim)
            if tuple(k.shape) != want or tuple(v.shape) != want:
                # roll the claim back before raising: a failed restore
                # must leave the pool exactly as it found it
                for p in pages:
                    self._refcounts[p] = 0
                self._free.extend(reversed(pages))
                raise ValueError(
                    f"restored chain layer {li}: block shape "
                    f"{tuple(k.shape)} != pool {want}")
            new_kv.append((K.at[:, idx].set(k), V.at[:, idx].set(v)))
        self.kv = new_kv
        if self.kv_scales is not None:
            self.kv_scales = [
                (Ks.at[:, idx].set(jnp.asarray(ent["Ks"], jnp.float32)),
                 Vs.at[:, idx].set(jnp.asarray(ent["Vs"], jnp.float32)))
                for (Ks, Vs), ent in zip(self.kv_scales, layers)]
        self._repin()
        for p in pages:
            self._pin_counts[p] = self._pin_counts.get(p, 0) + 1
        self._pins[chain_id] = (list(pages), num_tokens)
        return True

    def touch_pin(self, chain_id):
        """Refresh a chain's LRU recency (a probe hit keeps it hot)."""
        ent = self._pins.pop(chain_id)
        self._pins[chain_id] = ent

    def fork_pinned(self, seq_id, chain_id, num_tokens: int) -> list[int]:
        """Map a pinned chain's pages covering ``num_tokens`` tokens
        into a new sequence — the cold-prompt analog of :meth:`fork`
        (zero data movement, refcount + 1 per page). Touches the
        chain's LRU recency."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        pages, pinned_tokens = self._pins[chain_id]
        if num_tokens > pinned_tokens:
            raise ValueError(
                f"fork of {num_tokens} tokens exceeds the chain's "
                f"pinned {pinned_tokens}")
        shared = pages[:self.pages_for(num_tokens)]
        for p in shared:
            self._refcounts[p] += 1
        self._tables[seq_id] = list(shared)
        self._lens[seq_id] = num_tokens
        self.touch_pin(chain_id)
        return list(shared)

    # ---- queries ----
    def __contains__(self, seq_id) -> bool:
        return seq_id in self._tables

    def block_table(self, seq_id) -> list[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def set_seq_len(self, seq_id, n: int):
        if self.pages_for(n) > len(self._tables[seq_id]):
            raise ValueError(
                f"length {n} exceeds the {len(self._tables[seq_id])} pages "
                f"owned by {seq_id!r}; call extend() first")
        self._lens[seq_id] = n

    def rollback(self, seq_id, new_len: int):
        """Shrink a sequence's committed length after a speculative
        over-append (serving/spec_decode.py): the pages stay OWNED — the
        rejected tail's K/V slots are garbage the next append simply
        overwrites, and attention never reads past the committed length
        — only the attention/append cursor moves back. Freeing the tail
        pages instead would churn the allocator every rejected round for
        pages the sequence is about to grow back into."""
        cur = self._lens[seq_id]
        if new_len > cur:
            raise ValueError(
                f"rollback cannot grow {seq_id!r}: {cur} -> {new_len}")
        if new_len < 0:
            raise ValueError(f"negative rollback length {new_len}")
        self._lens[seq_id] = new_len

    def padded_block_table(self, seq_id, pages: int) -> list[int]:
        """Block table padded with NULL_PAGE to a fixed launch width."""
        table = self._tables[seq_id]
        if len(table) > pages:
            raise ValueError(
                f"{seq_id!r} owns {len(table)} pages > launch width {pages}")
        return table + [NULL_PAGE] * (pages - len(table))

    def live_sequences(self):
        return list(self._tables)

    def snapshot(self, offending_pages=()) -> dict:
        """Host-side pool state for failure triage (no device reads):
        nonzero refcounts, free-list size, pinned chain ids, sequence
        count, and the page ids the caller found offending. This is what
        :class:`InvariantViolation` carries out of a soak run."""
        return {
            "capacity": self.capacity,
            "used_pages": self.used_pages,
            "free_list_size": len(self._free),
            "refcounts": {p: rc for p, rc in enumerate(self._refcounts)
                          if rc},
            "pinned": list(self._pins),
            "pin_counts": dict(self._pin_counts),
            "num_sequences": len(self._tables),
            "offending_pages": sorted(set(offending_pages)),
        }

    def _invariant_fail(self, reason, pages=()):
        """Raise :class:`InvariantViolation` carrying a :meth:`snapshot`
        (and the flight recorder's last-N context when one is attached)
        — shared by :meth:`check_invariants` and the two-tier pool's
        residency audit (serving/kv_tier.py)."""
        err = InvariantViolation(reason, self.snapshot(pages))
        # always-on flight recorder (serving/tracing.py): the engine
        # back-references its recorder on the pool so a failing
        # audit ships the last-N steps of context WITH the exception
        # — a soak that dies mid-storm is triageable from the
        # artifact alone. A bare pool (unit tests) has no recorder.
        fr = getattr(self, "flight_recorder", None)
        if fr is not None:
            ctr = getattr(self, "flight_dump_counter", None)
            if ctr is not None:
                ctr.inc()
            err.flight_dump = fr.dump("invariant_violation",
                                      violation=reason)
        raise err

    def _resident_table(self, t):
        """Block-table entries that name RESIDENT pool pages — the hook
        the two-tier pool overrides (host-sentinel entries live in the
        arena and are audited by its own residency pass)."""
        return t

    def check_invariants(self):
        """Debug/test/soak hook: refcount/free-list/table consistency.

        - every mapped page's refcount equals the number of owners
          mapping it — sequence tables AND pinned chains both count —
          (and is therefore >= 1);
        - every free page has refcount 0 and no free page is mapped;
        - distinct physical pages in use + free pages == capacity;
        - the null page is never mapped and never on the free list;
        - pinned bookkeeping (_pin_counts) matches the pinned chains
          and stays within the pinned-page budget.

        A failure raises :class:`InvariantViolation` carrying a
        :meth:`snapshot` (refcounts, free-list size, pinned set, the
        offending page ids) instead of a bare assert.
        """
        fail = self._invariant_fail

        mapped: dict[int, int] = {}
        for sid, t in self._tables.items():
            seen_in_table = set()
            for p in self._resident_table(t):
                if p in seen_in_table:
                    fail(f"table {sid!r} maps pool page {p} twice", [p])
                seen_in_table.add(p)
                mapped[p] = mapped.get(p, 0) + 1
        pin_counts: dict[int, int] = {}
        for cid, (pages, num_tokens) in self._pins.items():
            if num_tokens % self.page_size != 0:
                fail(f"pinned chain {cid!r} is not page-aligned "
                     f"({num_tokens} tokens)", pages)
            for p in pages:
                mapped[p] = mapped.get(p, 0) + 1
                pin_counts[p] = pin_counts.get(p, 0) + 1
        if pin_counts != self._pin_counts:
            drift = set(pin_counts.items()) ^ set(self._pin_counts.items())
            fail(f"pin accounting drift: {pin_counts} != "
                 f"{self._pin_counts}", [p for p, _ in drift])
        if len(pin_counts) > max(self.pinned_page_budget, 0):
            fail(f"{len(pin_counts)} pinned pages exceed the "
                 f"pinned-page budget {self.pinned_page_budget}",
                 pin_counts)
        if NULL_PAGE in mapped:
            fail("null page leaked into a table", [NULL_PAGE])
        if NULL_PAGE in self._free:
            fail("null page on the free list", [NULL_PAGE])
        bad_rc = [p for p, owners in mapped.items()
                  if self._refcounts[p] != owners]
        if bad_rc:
            p = bad_rc[0]
            fail(f"page {p}: refcount {self._refcounts[p]} != "
                 f"{mapped[p]} owners", bad_rc)
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            dups = [p for p in free_set if self._free.count(p) > 1]
            fail("free list has duplicates", dups)
        if free_set & set(mapped):
            fail("page both mapped and free", free_set & set(mapped))
        bad_free = [p for p in self._free if self._refcounts[p] != 0]
        if bad_free:
            fail(f"free page {bad_free[0]} has refcount "
                 f"{self._refcounts[bad_free[0]]}", bad_free)
        if len(mapped) + len(self._free) != self.capacity:
            fail(f"page accounting leak: {len(mapped)} mapped + "
                 f"{len(self._free)} free != capacity {self.capacity}")
        if self.used_pages != len(mapped):
            fail(f"used_pages {self.used_pages} != {len(mapped)} "
                 f"mapped pages")
        return True


__all__ = ["InvariantViolation", "PagedKVPool", "PoolExhausted",
           "NULL_PAGE"]
