"""Paged KV-pool manager — the allocator under the serving engine.

The Pallas decode kernel (kernels/paged_attention.py) already consumes a
paged pool ``[num_kv_heads, num_pages, page_size, head_dim]`` plus per-
sequence block tables; what was missing above it is ownership: which pool
page belongs to which live sequence, and what happens when the pool runs
dry. This module is that layer (the TPU analog of vLLM's BlockSpaceManager
and of the reference's block_multi_head_attention cache manager):

- a free-list allocator over pool pages — page granularity means there is
  no external fragmentation by construction: any request for n free pages
  succeeds iff n pages are free;
- per-sequence block tables (logical page i of a sequence -> pool page),
  grown one page at a time as decode crosses page boundaries;
- pool page 0 is reserved as the NULL page: padded batch rows and padded
  block-table slots all point at it, so fixed-shape bucketed launches have
  a safe write/read target that never aliases live data;
- utilization watermarks the scheduler uses for admission control and
  preemption decisions.

Low-bit pools (``dtype=jnp.int8``): K/V pages are stored int8 with one
fp32 scale per (kv head, page) — ``kv_scales``, one (Ks, Vs) pair per
layer, shape [num_kv_heads, num_pages]. The engine quantizes on append
and the paged-attention kernel dequantizes at the gather (scales ride the
scalar-prefetch channel into SMEM). A page costs ~1/4 the fp32 bytes, so
the same HBM budget holds ~4x the pages (~2x vs bf16) and the scheduler
admits correspondingly more concurrent sequences at the same watermark —
``pages_for_byte_budget`` is the accounting the sizing test gates.

The device arrays themselves live in ``kv`` (one (K, V) pair per layer)
and are updated *functionally* by the engine's jitted prefill/decode steps
(the engine reassigns ``kv`` after each donated call); this class tracks
only the host-side ownership metadata.
"""
from __future__ import annotations

import jax.numpy as jnp


class PoolExhausted(RuntimeError):
    """Raised when an alloc/extend needs more free pages than exist."""


NULL_PAGE = 0


class PagedKVPool:
    """Free-list page allocator + per-sequence block tables over the pool.

    capacity = ``num_pages - 1`` allocatable pages (page 0 is the null
    page). ``seq_lens`` tracks the token count the engine has committed
    per sequence, so ``pages_needed`` and utilization stay in one place.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 page_size, dtype=jnp.float32, high_watermark=0.90,
                 low_watermark=0.50):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if not 0.0 < low_watermark <= high_watermark <= 1.0:
            raise ValueError("watermarks must satisfy 0 < low <= high <= 1")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.dtype = jnp.dtype(dtype)
        self.quantized = self.dtype == jnp.dtype(jnp.int8)
        shape = (num_kv_heads, num_pages, page_size, head_dim)
        self.kv = [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                   for _ in range(num_layers)]
        # per-(head, page) dequant scales for int8 pools; zero-init so a
        # fresh page's first append sets the scale from its own amax
        # instead of inheriting a fabricated range
        self.kv_scales = None
        if self.quantized:
            sshape = (num_kv_heads, num_pages)
            self.kv_scales = [(jnp.zeros(sshape, jnp.float32),
                               jnp.zeros(sshape, jnp.float32))
                              for _ in range(num_layers)]
        # LIFO free list: recently-freed pages are reused first (warm in
        # whatever cache level holds them)
        self._free = list(range(num_pages - 1, NULL_PAGE, -1))
        self._tables: dict[object, list[int]] = {}
        self._lens: dict[object, int] = {}

    # ---- byte accounting (pool sizing / bench fields) ----
    @staticmethod
    def page_bytes_for(num_layers, num_kv_heads, head_dim, page_size,
                       dtype=jnp.float32) -> int:
        """HBM bytes one pool page costs across all layers, K+V, scale
        rows included for int8 pools."""
        dt = jnp.dtype(dtype)
        data = num_layers * 2 * num_kv_heads * page_size * head_dim \
            * dt.itemsize
        scales = num_layers * 2 * num_kv_heads * 4 \
            if dt == jnp.dtype(jnp.int8) else 0
        return data + scales

    @classmethod
    def pages_for_byte_budget(cls, byte_budget, num_layers, num_kv_heads,
                              head_dim, page_size,
                              dtype=jnp.float32) -> int:
        """Largest ``num_pages`` whose pool fits ``byte_budget`` — how an
        operator sizes fp32 vs int8 pools at the same HBM watermark (the
        ~2x-sequences-per-byte win the int8 pool exists for)."""
        per = cls.page_bytes_for(num_layers, num_kv_heads, head_dim,
                                 page_size, dtype)
        return max(int(byte_budget) // per, 0)

    @property
    def page_bytes(self) -> int:
        return self.page_bytes_for(self.num_layers, self.num_kv_heads,
                                   self.head_dim, self.page_size,
                                   self.dtype)

    @property
    def kv_bytes_per_token(self) -> float:
        """Bytes of pool one cached token occupies (scale rows amortized
        over the page's tokens) — bench.py's ``kv_bytes_per_token``."""
        return self.page_bytes / self.page_size

    @property
    def pool_bytes(self) -> int:
        return self.page_bytes * self.num_pages

    # ---- capacity ----
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    @property
    def utilization(self) -> float:
        return self.used_pages / self.capacity

    def above_high_watermark(self, extra_pages=0) -> bool:
        return (self.used_pages + extra_pages) / self.capacity \
            > self.high_watermark

    def below_low_watermark(self) -> bool:
        return self.utilization < self.low_watermark

    def pages_for(self, num_tokens: int) -> int:
        return -(-max(num_tokens, 0) // self.page_size)

    def can_allocate(self, num_tokens: int) -> bool:
        return self.pages_for(num_tokens) <= len(self._free)

    # ---- lifecycle ----
    def allocate(self, seq_id, num_tokens: int) -> list[int]:
        """Claim pages for a new sequence of ``num_tokens`` tokens."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        n = self.pages_for(num_tokens)
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages for {num_tokens} tokens, "
                f"{len(self._free)} free of {self.capacity}")
        pages = [self._free.pop() for _ in range(n)]
        self._tables[seq_id] = pages
        self._lens[seq_id] = num_tokens
        return pages

    def extend(self, seq_id, new_len: int) -> list[int]:
        """Grow ``seq_id``'s table to cover ``new_len`` tokens; returns the
        newly claimed pages (possibly empty). All-or-nothing on exhaustion.
        """
        table = self._tables[seq_id]
        need = self.pages_for(new_len) - len(table)
        if need > len(self._free):
            raise PoolExhausted(
                f"sequence {seq_id!r} needs {need} more pages, "
                f"{len(self._free)} free of {self.capacity}")
        fresh = [self._free.pop() for _ in range(max(need, 0))]
        table.extend(fresh)
        self._lens[seq_id] = max(new_len, self._lens[seq_id])
        return fresh

    def free(self, seq_id) -> int:
        """Release every page the sequence owns; returns the page count."""
        pages = self._tables.pop(seq_id)
        self._lens.pop(seq_id, None)
        self._free.extend(reversed(pages))
        if self.kv_scales is not None and pages:
            # reset the freed pages' dequant scales: the append path's
            # running max (engine._quantized_append) only ever GROWS a
            # scale, so a recycled page must not hand its next tenant the
            # previous sequence's (possibly much larger) range — that
            # would quantize small new values straight to zero
            idx = jnp.asarray(pages, jnp.int32)
            self.kv_scales = [(Ks.at[:, idx].set(0.0),
                               Vs.at[:, idx].set(0.0))
                              for Ks, Vs in self.kv_scales]
        return len(pages)

    # ---- queries ----
    def __contains__(self, seq_id) -> bool:
        return seq_id in self._tables

    def block_table(self, seq_id) -> list[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def set_seq_len(self, seq_id, n: int):
        if self.pages_for(n) > len(self._tables[seq_id]):
            raise ValueError(
                f"length {n} exceeds the {len(self._tables[seq_id])} pages "
                f"owned by {seq_id!r}; call extend() first")
        self._lens[seq_id] = n

    def padded_block_table(self, seq_id, pages: int) -> list[int]:
        """Block table padded with NULL_PAGE to a fixed bucket width."""
        table = self._tables[seq_id]
        if len(table) > pages:
            raise ValueError(
                f"{seq_id!r} owns {len(table)} pages > bucket {pages}")
        return table + [NULL_PAGE] * (pages - len(table))

    def live_sequences(self):
        return list(self._tables)

    def check_invariants(self):
        """Debug/test hook: every page owned exactly once, free+used=cap."""
        owned = [p for t in self._tables.values() for p in t]
        seen = set(owned)
        assert len(owned) == len(seen), "a pool page is owned twice"
        assert NULL_PAGE not in seen, "null page leaked into a block table"
        assert not (seen & set(self._free)), "page both owned and free"
        assert len(owned) + len(self._free) == self.capacity, \
            "page accounting leak"
        return True


__all__ = ["PagedKVPool", "PoolExhausted", "NULL_PAGE"]
