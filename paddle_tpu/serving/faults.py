"""Fault injection for cluster-scale serving — seeded, virtual-clock,
byte-reproducible.

A fleet's p99/goodput story is only as good as its behavior when
replicas crash, drain, and slow down. This module makes those faults
*data*: a :class:`FaultSchedule` is an explicit, sorted list of
:class:`FaultEvent`\\ s on the loadgen virtual clock
(paddle_tpu/loadgen/driver.py), consumed by the cluster router
(serving/cluster.py ``ClusterEngine``) at step boundaries. Because the
schedule is plain data and every timestamp is virtual, a fault run is as
deterministic as a fault-free one — the same seed reproduces the same
crashes, the same requeues, and the same report bytes, chip-free
(docs/ROBUSTNESS.md maps each fault kind to the claim it proves).

Fault kinds:

- ``crash`` — the replica dies instantly: its engine (KV pool included)
  is discarded, every request assigned to it is requeued to a survivor
  (retry budget permitting), and the replica sits DOWN until
  ``recover_s`` later, when a fresh engine warms up through RECOVERING.
- ``drain`` — graceful shutdown rehearsal: admission freezes for
  ``duration_s``, waiting requests are requeued to survivors, running
  requests finish in place.
- ``slowdown`` — the replica's per-step latency is multiplied by
  ``magnitude`` for ``duration_s``: it executes one engine step every
  ``magnitude`` cluster rounds, so its consecutive-step latency (and
  its health score) degrade exactly as a thermally-throttled or
  noisy-neighbor chip's would.
- ``kv_pressure`` — a ballast allocation pins ``magnitude`` of the
  replica's pool capacity for ``duration_s``: watermark admission
  control, preemption, and the degradation ladder all see genuine page
  pressure without any traffic change.
- ``flaky`` — every step attempt in the window raises a transient
  :class:`InjectedFault`; the cluster absorbs each one (the step is
  lost, requests stay put) until ``crash_after_flaky`` consecutive
  failures escalate the replica to a crash.
- ``transfer_slow`` — the KV fabric (serving/fabric.py) multiplies the
  modeled transfer latency of every page transfer issued FROM the
  replica by ``magnitude`` for ``duration_s``: in-flight handoffs land
  late, the fabric's stall counter moves, and the collapse-to-colocated
  hysteresis sees genuine degradation without any traffic change.
- ``transfer_drop`` — every page transfer issued from the replica
  inside the window is dropped after its modeled latency elapses: the
  cluster counts the drop and requeues the request as a fresh retry
  (recompute keeps correctness), exercising the fabric's retry path.
"""
from __future__ import annotations

from dataclasses import asdict, dataclass

import numpy as np

# new kinds append at the END: FaultSchedule's sort tie-break uses
# KINDS.index(kind), so reordering would change the firing order of
# same-instant faults and break recorded report bytes
KINDS = ("crash", "drain", "slowdown", "kv_pressure", "flaky",
         "transfer_slow", "transfer_drop")


class InjectedFault(RuntimeError):
    """The transient exception a scheduled flaky-step fault raises in
    place of a replica's engine step. The cluster catches it, counts
    it, and carries on — a fleet must survive a step that throws."""


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: fires when the virtual clock reaches ``t``.

    ``duration_s`` bounds the window faults (drain / slowdown /
    kv_pressure / flaky / transfer_slow / transfer_drop); ``recover_s``
    is crash-only (DOWN -> RECOVERING delay; None = the replica never
    comes back); ``magnitude`` is the slowdown's or transfer_slow's
    latency multiplier (> 1) or the kv_pressure ballast as a fraction
    of pool capacity (0, 1]."""
    t: float
    replica: int
    kind: str
    duration_s: float = 0.0
    recover_s: float | None = None
    magnitude: float = 2.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"fault kind must be one of {KINDS}, "
                             f"got {self.kind!r}")
        if self.t < 0:
            raise ValueError(f"fault time must be >= 0, got {self.t}")
        if self.replica < 0:
            raise ValueError(f"replica index must be >= 0, "
                             f"got {self.replica}")
        if self.kind != "crash" and self.duration_s <= 0:
            raise ValueError(
                f"{self.kind} needs duration_s > 0, got {self.duration_s}")
        if self.kind == "crash" and self.recover_s is not None \
                and self.recover_s <= 0:
            raise ValueError(
                f"crash recover_s must be > 0 or None (never recovers), "
                f"got {self.recover_s}")
        if self.kind == "slowdown" and self.magnitude <= 1.0:
            raise ValueError(
                f"slowdown magnitude is a latency multiplier > 1, "
                f"got {self.magnitude}")
        if self.kind == "kv_pressure" and not 0.0 < self.magnitude <= 1.0:
            raise ValueError(
                f"kv_pressure magnitude is a capacity fraction in "
                f"(0, 1], got {self.magnitude}")
        if self.kind == "transfer_slow" and self.magnitude <= 1.0:
            raise ValueError(
                f"transfer_slow magnitude is a transfer-latency "
                f"multiplier > 1, got {self.magnitude}")


class FaultSchedule:
    """An immutable, time-sorted fault script. The cluster keeps its own
    read cursor, so one schedule object can parameterize any number of
    runs — byte-reproducibility needs no reset discipline."""

    def __init__(self, events):
        events = list(events)
        for e in events:
            if not isinstance(e, FaultEvent):
                raise TypeError(f"FaultSchedule takes FaultEvents, "
                                f"got {type(e).__name__}")
        #: sorted copy — ties break on (replica, kind) so the firing
        #: order (and therefore every downstream requeue) is total
        self.events = tuple(sorted(
            events, key=lambda e: (e.t, e.replica, KINDS.index(e.kind))))

    def __len__(self):
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def describe(self) -> list:
        """Plain-dict view for the cluster report artifact."""
        return [asdict(e) for e in self.events]

    @classmethod
    def generate(cls, *, seed, num_replicas, horizon_s, events_per_replica=2,
                 kinds=("crash", "drain", "slowdown"), duration_s=(0.1, 0.5),
                 recover_s=(0.2, 0.6), slowdown=(2.0, 4.0),
                 kv_fraction=(0.3, 0.7)) -> "FaultSchedule":
        """Seeded random schedule: ``events_per_replica`` faults per
        replica, kinds/times/durations off ONE numpy Generator — the
        same seed compiles the same script, the fault-side analog of
        ``WorkloadSpec.compile()``."""
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        for k in kinds:
            if k not in KINDS:
                raise ValueError(f"unknown fault kind {k!r}")
        rng = np.random.default_rng(seed)
        events = []
        for rid in range(num_replicas):
            for _ in range(events_per_replica):
                kind = kinds[int(rng.integers(0, len(kinds)))]
                t = float(rng.uniform(0.0, horizon_s))
                kw = {}
                if kind == "crash":
                    kw["recover_s"] = float(rng.uniform(*recover_s))
                else:
                    kw["duration_s"] = float(rng.uniform(*duration_s))
                if kind == "slowdown":
                    kw["magnitude"] = float(rng.uniform(*slowdown))
                elif kind == "kv_pressure":
                    kw["magnitude"] = float(rng.uniform(*kv_fraction))
                events.append(FaultEvent(t=t, replica=rid, kind=kind, **kw))
        return cls(events)


__all__ = ["FaultEvent", "FaultSchedule", "InjectedFault", "KINDS"]
