"""paddle_tpu.serving — continuous-batching LLM serving on TPU.

Layers (docs/SERVING.md has the full architecture):

- :mod:`kv_cache` — ``PagedKVPool``: refcounted free-list page allocator
  + per-sequence block tables over the pool layout the Pallas ragged
  kernel (kernels/paged_attention.py) consumes, with copy-on-write
  prefix-page sharing (``fork``/``prepare_append``).
- :mod:`kv_tier` — ``HostKVArena`` + ``TieredKVPool`` +
  ``KVPrefetcher``: the host-RAM spill tier under the paged pool
  (``LLMEngine(host_kv_pages=N)``) — preemption victims park with an
  exact-byte spill instead of recomputing, cursor-ahead background
  staging restores them ahead of re-admission, and live context is
  bounded by hbm + host pages instead of HBM alone.
- :mod:`scheduler` — ``Scheduler``: FIFO admission, chunked-prefill
  ragged step planning (decode rows and prompt chunks in ONE launch),
  deadline load shedding, preemption-with-requeue (spill-park first
  on two-tier pools).
- :mod:`engine` — ``LLMEngine`` + ``Request``/``RequestOutput``: the
  request lifecycle over ONE jitted fixed-shape ragged step, with a
  prefix-hash cache that admits repeated prompt prefixes by forking
  pages instead of re-prefilling. ``RequestRejected`` is the structured
  admission error for unserviceable requests.
- :mod:`spec_decode` — ``DraftWorker`` + ``speculative_sample``:
  int4-draft speculative decoding with one-pass ragged verification
  and exact rejection sampling (``LLMEngine(draft_model=...)``).
- :mod:`metrics` — ``ServingMetrics``: counters/gauges exported to
  bench.py and the profiler timeline.
- :mod:`cluster` — ``ClusterEngine`` + ``DegradationLadder`` +
  ``ReplicaState``: N replicas behind a health-aware router with a
  replica lifecycle state machine, retry-with-backoff requeue, and a
  hysteretic graceful-degradation ladder per replica.
- :mod:`faults` — ``FaultSchedule``/``FaultEvent``: seeded,
  virtual-clock fault injection (crash/drain/slowdown/kv-pressure/
  flaky/transfer-slow/transfer-drop) so fleet robustness claims
  reproduce byte-for-byte chip-free.
- :mod:`fabric` — ``KVFabric`` + ``TransferModel`` +
  ``FleetPrefixCache``: the page-granular KV transfer fabric behind
  disaggregated prefill/decode serving (``ClusterEngine(roles=...)``)
  — finished prefill KV pages stream to the assigned decode replica
  on the virtual clock, and content-addressed pinned prefix chains
  publish fleet-wide so any replica faults them in without a
  re-prefill.
"""
from .kv_cache import (InvariantViolation, PagedKVPool,  # noqa: F401
                       PoolExhausted, NULL_PAGE)
from .kv_tier import (ArenaExhausted, HostKVArena,  # noqa: F401
                      KVPrefetcher, TieredKVPool)
from .scheduler import (BurstPlan, Scheduler, SchedulerConfig,  # noqa: F401
                        Sequence, SequenceStatus, StepPlan, bucket_for)
from .spec_decode import DraftWorker, speculative_sample  # noqa: F401
from .engine import (LLMEngine, PrefixStoreMismatch,  # noqa: F401
                     Request, RequestOutput, RequestRejected)
from .metrics import (Histogram, ServingMetrics,  # noqa: F401
                      percentile_of)
from .faults import (FaultEvent, FaultSchedule,  # noqa: F401
                     InjectedFault)
from .tracing import (FlightRecorder, RequestTracer,  # noqa: F401
                      latency_breakdown, request_breakdown)
from .fabric import (FleetPrefixCache, KVFabric,  # noqa: F401
                     Transfer, TransferModel)
from .cluster import (ClusterEngine, DegradationLadder,  # noqa: F401
                      FleetDegradation, ReplicaState)

__all__ = ["ArenaExhausted", "BurstPlan", "ClusterEngine",
           "DegradationLadder",
           "DraftWorker", "FaultEvent", "FaultSchedule",
           "FleetDegradation", "FleetPrefixCache",
           "FlightRecorder", "Histogram", "HostKVArena", "KVPrefetcher",
           "KVFabric", "TieredKVPool", "Transfer", "TransferModel",
           "InjectedFault", "InvariantViolation", "LLMEngine",
           "Request", "RequestOutput", "RequestRejected", "PagedKVPool",
           "PoolExhausted", "PrefixStoreMismatch", "NULL_PAGE",
           "ReplicaState", "RequestTracer",
           "Scheduler",
           "SchedulerConfig", "Sequence", "SequenceStatus", "StepPlan",
           "ServingMetrics", "bucket_for", "latency_breakdown",
           "percentile_of", "request_breakdown", "speculative_sample"]
