"""paddle_tpu.serving — continuous-batching LLM serving on TPU.

Layers (docs/SERVING.md has the full architecture):

- :mod:`kv_cache` — ``PagedKVPool``: free-list page allocator + per-
  sequence block tables over the pool layout the Pallas decode kernel
  (kernels/paged_attention.py) consumes.
- :mod:`scheduler` — ``Scheduler``: FIFO admission, fixed-shape decode
  bucket assembly, deadline load shedding, preemption-with-requeue.
- :mod:`engine` — ``LLMEngine`` + ``Request``/``RequestOutput``: the
  request lifecycle over bucketed jitted prefill/decode steps.
- :mod:`metrics` — ``ServingMetrics``: counters/gauges exported to
  bench.py and the profiler timeline.
"""
from .kv_cache import PagedKVPool, PoolExhausted, NULL_PAGE  # noqa: F401
from .scheduler import (Scheduler, SchedulerConfig, Sequence,  # noqa: F401
                        SequenceStatus, bucket_for)
from .engine import LLMEngine, Request, RequestOutput  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401

__all__ = ["LLMEngine", "Request", "RequestOutput", "PagedKVPool",
           "PoolExhausted", "NULL_PAGE", "Scheduler", "SchedulerConfig",
           "Sequence", "SequenceStatus", "ServingMetrics", "bucket_for"]
