"""Page-granular KV fabric for disaggregated prefill/decode serving.

Disaggregation splits a fleet into a PREFILL pool (long-prompt chew,
row slots recycle as soon as the first token samples) and a DECODE pool
(steady token emission, never starved by a neighbour's 32k-token
prompt). The piece that makes the split real is moving a finished
prompt's KV pages from the prefill replica to its assigned decode
replica — this module models that wire.

Three cooperating parts, all on the loadgen virtual clock (no wall
time anywhere, so a disaggregated run is byte-reproducible per seed):

- :class:`TransferModel` — the cost model: a page transfer costs
  ``base_s + page_s * pages``. Defaults approximate host-staged
  ``device_put`` over DCN; docs/PERF.md §17 derives both constants and
  contrasts them with real ICI collectives.
- :class:`KVFabric` — the transfer engine: bounded in-flight depth
  (the same discipline as :class:`~paddle_tpu.serving.kv_tier.
  KVPrefetcher`'s queue — refusal is back-pressure, counted by the
  caller as a ``transfer_stall``, never a hang), per-source fault
  windows (``transfer_slow`` multiplies modeled latency,
  ``transfer_drop`` loses the payload after the latency elapses so the
  retry path is exercised honestly), and a *streaming credit*: each
  chunked-prefill boundary the source replica reports moves that
  request's finished pages early, so the final handoff only pays for
  the last chunk's pages. Chunk boundaries — not whole prompts — are
  the streaming unit.
- :class:`FleetPrefixCache` — the fleet-wide generalization of the
  per-engine pinned-prefix store: content-addressed pinned chains
  (the key IS the token tuple) published into a shared
  :class:`~paddle_tpu.io.persist.ArtifactStore` that ANY replica in
  either pool can fault into its own HBM or host tier. A prompt
  prefilled once anywhere is never re-prefilled anywhere — including
  after the publishing replica crashes, because the bytes live in the
  shared store, not in the dead replica's pool.

The fabric never touches devices: payloads are the host-side ``layers``
wire format every other KV mover in this codebase already speaks
(``HostKVArena.write``/``read``, ``export_pinned``,
``restore_pinned_chain``, ``export_pages``/``adopt_sequence``) — a
list of per-layer ``{"K", "V"[, "Ks", "Vs"]}`` dicts of numpy blocks.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["TransferModel", "Transfer", "KVFabric", "FleetPrefixCache"]


@dataclass(frozen=True)
class TransferModel:
    """Latency model for one KV handoff: ``base_s + page_s * pages``.

    ``base_s`` is the per-transfer setup cost (RPC + host staging);
    ``page_s`` the per-page wire cost. Both are virtual seconds. The
    defaults model host-staged DCN transfers of ~page_bytes pages; a
    real ICI fabric would shrink ``page_s`` by ~two orders of magnitude
    (docs/PERF.md §17) without changing any of the control flow here.
    """
    base_s: float = 0.002
    page_s: float = 0.0005

    def __post_init__(self):
        if self.base_s < 0 or self.page_s < 0:
            raise ValueError(
                f"TransferModel costs must be >= 0, got "
                f"base_s={self.base_s}, page_s={self.page_s}")

    def latency(self, pages: int) -> float:
        return self.base_s + self.page_s * max(int(pages), 0)


@dataclass
class Transfer:
    """One in-flight (or landed) handoff. ``payload`` is the engine's
    ``extract_request`` dict; ``pages`` the billed page count (after
    streaming credit); ``dropped`` marks a transfer_drop casualty —
    it still lands at ``ready_at`` so the cluster can count the loss
    and requeue, but its payload must not be injected."""
    rid: str
    payload: dict
    src: int
    dst: int
    pages: int
    issued_at: float
    ready_at: float
    dropped: bool = False
    order: int = field(default=0, compare=False)


class KVFabric:
    """Bounded, fault-aware, virtual-clock KV transfer engine.

    ``depth`` bounds concurrent in-flight transfers fleet-wide —
    ``issue`` refuses (returns False) when full, and the caller counts
    a stall and retries next round, exactly the KVPrefetcher queue
    discipline. All state advances only through method calls carrying
    the caller's clock, so two runs with the same seed replay the same
    transfers to the byte.

    Lifetime counters (host-side ints, mirrored into the cluster
    report): ``issued``, ``landed``, ``pages_sent``, ``refusals``,
    ``drops``, ``pages_streamed``.
    """

    def __init__(self, model: TransferModel | None = None, *, depth: int = 4):
        if depth < 1:
            raise ValueError(f"KVFabric depth must be >= 1, got {depth}")
        self.model = model if model is not None else TransferModel()
        self.depth = int(depth)
        self._inflight: list[Transfer] = []
        self._order = 0
        #: request -> pages already streamed at chunk boundaries
        self._credit: dict = {}
        #: replica -> (until, magnitude) / replica -> until
        self._slow: dict = {}
        self._drop: dict = {}
        self.counters = {"issued": 0, "landed": 0, "pages_sent": 0,
                         "refusals": 0, "drops": 0, "pages_streamed": 0}

    # ---- fault windows (serving/faults.py transfer_* kinds) ----
    def set_slow(self, replica: int, until: float, magnitude: float):
        if magnitude <= 1.0:
            raise ValueError(
                f"transfer_slow magnitude must be > 1, got {magnitude}")
        self._slow[int(replica)] = (float(until), float(magnitude))

    def set_drop(self, replica: int, until: float):
        self._drop[int(replica)] = float(until)

    def _slow_factor(self, src: int, dst: int, now: float) -> float:
        # a degraded link at EITHER endpoint slows the transfer; two
        # live windows compound (both NICs are sick)
        factor = 1.0
        for rep in (src, dst) if src != dst else (src,):
            ent = self._slow.get(rep)
            if ent is not None and now < ent[0]:
                factor *= ent[1]
        return factor

    def _dropped(self, src: int, dst: int, now: float) -> bool:
        return any(until is not None and now < until
                   for until in (self._drop.get(src),
                                 self._drop.get(dst)))

    # ---- streaming credit (chunked-prefill boundaries) ----
    def stream(self, rid: str, pages_done: int):
        """A chunk boundary finished ``pages_done`` total pages for
        ``rid`` on its prefill replica: the fabric streams the delta
        ahead of the handoff. Credit is monotonic; the eventual
        ``issue`` bills only the pages NOT already streamed."""
        prev = self._credit.get(rid, 0)
        pages_done = max(int(pages_done), 0)
        if pages_done > prev:
            self.counters["pages_streamed"] += pages_done - prev
            self._credit[rid] = pages_done

    def credit(self, rid: str) -> int:
        return self._credit.get(rid, 0)

    # ---- transfers ----
    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    def issue(self, rid, payload, *, src, dst, pages, now) -> bool:
        """Launch one handoff. False = depth-refused (back-pressure;
        the caller counts a ``transfer_stall`` and retries next round).
        The billed page count nets out streaming credit — a fully
        streamed request still pays ``base_s`` for the final control
        handoff. A live ``transfer_drop`` window on ``src`` marks the
        transfer lost; it lands at ``ready_at`` as a casualty so the
        retry is driven by the same clock as a success."""
        if len(self._inflight) >= self.depth:
            self.counters["refusals"] += 1
            return False
        billed = max(int(pages) - self._credit.pop(rid, 0), 0)
        latency = self.model.latency(billed) \
            * self._slow_factor(src, dst, now)
        tr = Transfer(rid=rid, payload=payload, src=int(src), dst=int(dst),
                      pages=int(pages), issued_at=float(now),
                      ready_at=float(now) + latency,
                      dropped=self._dropped(src, dst, now),
                      order=self._order)
        self._order += 1
        self._inflight.append(tr)
        self.counters["issued"] += 1
        self.counters["pages_sent"] += billed
        if tr.dropped:
            self.counters["drops"] += 1
        return True

    def take_ready(self, now: float) -> list:
        """Transfers whose modeled latency has elapsed, in a total
        deterministic order (ready_at, issue order). Dropped transfers
        are returned too — the caller requeues those instead of
        injecting."""
        ready = [t for t in self._inflight if t.ready_at <= now]
        if not ready:
            return []
        ready.sort(key=lambda t: (t.ready_at, t.order))
        self._inflight = [t for t in self._inflight if t.ready_at > now]
        self.counters["landed"] += sum(1 for t in ready if not t.dropped)
        return ready

    def cancel_dst(self, replica: int) -> list:
        """Pull every in-flight transfer destined for ``replica`` (it
        crashed / collapsed): the caller requeues the payloads as fresh
        retries. Deterministic issue order."""
        out = [t for t in self._inflight if t.dst == int(replica)]
        if out:
            out.sort(key=lambda t: t.order)
            self._inflight = [t for t in self._inflight
                              if t.dst != int(replica)]
        return out

    def forget(self, rid: str):
        """Drop streaming credit for a finished/aborted request."""
        self._credit.pop(rid, None)


def _chain_tag(tokens) -> str:
    """Content-addressed ArtifactStore tag for a pinned chain: the key
    IS the token tuple, hashed for filesystem friendliness."""
    h = hashlib.sha1(",".join(str(int(t)) for t in tokens).encode())
    return "fleetpfx-" + h.hexdigest()[:20]


class FleetPrefixCache:
    """Fleet-wide content-addressed prefix cache over a shared
    :class:`~paddle_tpu.io.persist.ArtifactStore`.

    ``publish`` is called by an engine after it pins a prompt's full
    pages (``_register_prefix``): the chain's layers land in the shared
    store under a tag derived from the token tuple, and the fleet index
    maps every page-aligned prefix of the chain to it. ``lookup`` is
    the admission-side probe any OTHER replica runs on a local miss:
    an exact page-aligned prefix match returns the layers (checksum-
    verified through the store), which the engine lands via
    ``restore_pinned_chain`` + ``fork_pinned`` — the same two-tier
    machinery the warm-restart prefix store uses.

    The index is in-memory fleet-scope state (it lives in the cluster,
    not in any replica), so it survives replica crashes; the page BYTES
    are durable in the store. ``capacity`` LRU-bounds published chains.
    Geometry safety: a chain publishes with its pool config and a
    lookup from a mismatched pool is a miss, never a wrong-shape fork.

    With ``store=None`` the cache runs memory-backed (chains held as
    host arrays) — same semantics minus crash durability.
    """

    def __init__(self, store=None, *, capacity: int = 256):
        if capacity < 1:
            raise ValueError(
                f"FleetPrefixCache capacity must be >= 1, got {capacity}")
        self.store = store
        self.capacity = int(capacity)
        #: page-aligned prefix tuple -> (chain tuple, shared length)
        self._index: dict = {}
        #: chain tuple -> (num_tokens, config, payload-or-None)
        self._chains: dict = {}
        self.counters = {"publishes": 0, "hits": 0, "misses": 0,
                         "config_rejects": 0}

    def __len__(self):
        return len(self._chains)

    def contains(self, chain) -> bool:
        return tuple(chain) in self._chains

    def publish(self, chain, num_tokens, layers, config, *, page_size):
        """Index ``chain`` (a full-page token tuple) fleet-wide. No-op
        when already published (content-addressed: same tokens = same
        bytes). Evicts the oldest chain past ``capacity``."""
        chain = tuple(int(t) for t in chain)
        if chain in self._chains:
            return False
        payload = None
        if self.store is not None:
            arrays = {}
            for li, ent in enumerate(layers):
                for part, arr in ent.items():
                    arrays[f"L{li}/{part}"] = np.asarray(arr)
            meta = {"format": 1, "config": dict(config),
                    "tokens": list(chain), "num_tokens": int(num_tokens)}
            self.store.save(_chain_tag(chain), arrays, meta)
        else:
            payload = [{k: np.asarray(v) for k, v in ent.items()}
                       for ent in layers]
        self._chains[chain] = (int(num_tokens), dict(config), payload)
        for j in range(int(page_size), int(num_tokens) + 1, int(page_size)):
            key = chain[:j]
            self._index.pop(key, None)
            self._index[key] = (chain, j)
        while len(self._chains) > self.capacity:
            old = next(iter(self._chains))
            self._evict(old)
        self.counters["publishes"] += 1
        return True

    def _evict(self, chain):
        self._chains.pop(chain, None)
        self._index = {k: v for k, v in self._index.items()
                       if v[0] != chain}

    def lookup(self, prefix, config):
        """Exact page-aligned prefix match -> ``(chain, num_tokens,
        layers)``; None on miss. ``config`` must equal the publishing
        pool's (shape drift = miss, counted). Store-backed chains whose
        every version fails verification are evicted and missed —
        checksummed bytes or nothing."""
        ent = self._index.get(tuple(int(t) for t in prefix))
        if ent is None:
            self.counters["misses"] += 1
            return None
        chain, _j = ent
        num_tokens, cfg, payload = self._chains[chain]
        if dict(config) != cfg:
            self.counters["config_rejects"] += 1
            self.counters["misses"] += 1
            return None
        if payload is None:
            res = self.store.load(_chain_tag(chain))
            if res is None:
                self._evict(chain)
                self.counters["misses"] += 1
                return None
            num_layers = len({k.split("/")[0] for k in res.arrays})
            payload = []
            for li in range(num_layers):
                lent = {"K": res.arrays[f"L{li}/K"],
                        "V": res.arrays[f"L{li}/V"]}
                if f"L{li}/Ks" in res.arrays:
                    lent["Ks"] = res.arrays[f"L{li}/Ks"]
                    lent["Vs"] = res.arrays[f"L{li}/Vs"]
                payload.append(lent)
        self.counters["hits"] += 1
        return chain, num_tokens, payload
