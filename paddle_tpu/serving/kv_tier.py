"""Two-tier KV cache: host-RAM spill arena + cursor-ahead prefetch.

ROADMAP item 5(a): every serving PR so far treated HBM as the only home
for KV pages, so the engine's live context capacity — how many committed
tokens the fleet can hold at once — was HBM-bound. This module adds the
classic paged-attention memory-hierarchy move (vLLM's swap tier,
PAPERS.md) on top of :class:`~paddle_tpu.serving.kv_cache.PagedKVPool`:

- :class:`HostKVArena` — a host-RAM page store: numpy-backed page slabs
  (one ``[Hkv, host_pages, page_size, head_dim]`` K and V slab per
  layer, plus per-(head, page) fp32 scale columns for int8 pools) under
  a LIFO free list identical in spirit to the pool's. Pages here are
  bytes at rest: nothing ever computes against the arena.
- :class:`TieredKVPool` — a :class:`PagedKVPool` whose pages can live in
  either tier. Under HBM pressure the scheduler PARKS a victim sequence
  instead of recompute-preempting it: the victim's **cold** pages — its
  exclusively-owned, unpinned pages; a parked row is in no launch, so no
  reader's causal horizon covers them — spill to the arena (exact bytes,
  int8 scale columns included) and the HBM pages recycle. Pinned prefix
  chains and CoW-shared pages are never spilled: a shared page may be
  read by a live sequence this very step, and pins are the prefix
  cache's rc floor — both stay HBM-resident. Spill order over parked
  sequences is LRU by last touch on the pool's virtual round clock,
  with ties broken by one seeded stream — byte-reproducible per seed.
- :class:`KVPrefetcher` — the background staging lane
  (``io/prefetch.py``'s thread+bounded-queue discipline, KV edition):
  the engine issues restores for parked sequences *ahead of the decode/
  prefill cursor* — at the end of the step before re-admission could
  want them — and a daemon thread stages the arena blocks onto the
  device (``jax.device_put`` is an async dispatch under PJRT, so the
  H2D copy overlaps the next step's compute on a real chip). At claim
  time the main thread scatters the staged blocks into freshly claimed
  pool pages.

Residency contract (the part the ragged step depends on): a sequence's
block-table entry is either a resident pool page (``>= 1``) or a host
sentinel ``-(arena_slot + 1)`` (``<= -1``). Only fully-resident
sequences are ever scheduled into a launch — ``padded_block_table``
hard-fails on a host sentinel, and ``check_invariants`` audits that
every page lives in exactly one tier. Decode therefore NEVER reads a
non-resident page; when a restore was not staged a full round ahead
(the prefetch lost the race to the cursor), the engine charges a
**counted, bounded stall** (``kv_prefetch_stalls`` + a flight event):
the restore happens synchronously on the main thread, tokens stay
bit-identical, only the overlap is lost.

Determinism: hit-vs-stall classification compares the prefetch's ISSUE
round against the restore's CLAIM round on the pool's virtual clock —
never wall-clock thread completion — so a seeded loadgen run reports
byte-identical spill/prefetch/stall counts on every run while the
staging thread still does real asynchronous work. A restore consumes
staged bytes when they exist and falls back to a synchronous copy when
they don't; the data is identical either way.

Capacity story: live context (committed tokens across admitted
sequences, pinned chains included) is bounded by ``(hbm_pages +
host_pages) * page_size`` instead of HBM alone. One RUNNING row must
still be fully HBM-resident for its launch — full causal attention
reads the row's whole history every step — so a single request's
context stays bounded by ``min(max_pages_per_seq, hbm capacity)``;
docs/PERF.md §16 spells out what would change on a chip (per-layer KV
streaming) to lift that too.
"""
from __future__ import annotations

import queue
import random
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import PagedKVPool, PoolExhausted


class ArenaExhausted(PoolExhausted):
    """Raised when a host-arena claim needs more free slots than exist.
    Subclasses :class:`PoolExhausted` so pressure ladders that already
    answer pool exhaustion handle the host tier the same way."""


class HostKVArena:
    """Host-RAM page store: numpy slabs + free list, no compute.

    One slot holds one pool page across every layer (K and V blocks,
    plus the page's per-(head, page) scale columns for int8 pools).
    ``claim``/``release`` mirror the pool's free-list discipline —
    LIFO, all-or-nothing — and ``write``/``read`` move exact bytes, so
    a spill/restore round trip is bit-identical by construction.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 page_size, dtype=jnp.float32):
        if num_pages < 1:
            raise ValueError("HostKVArena needs num_pages >= 1")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_pages = int(num_pages)
        self.page_size = page_size
        self.dtype = jnp.dtype(dtype)
        self.quantized = self.dtype == jnp.dtype(jnp.int8)
        shape = (num_kv_heads, self.num_pages, page_size, head_dim)
        self._k = [np.zeros(shape, self.dtype) for _ in range(num_layers)]
        self._v = [np.zeros(shape, self.dtype) for _ in range(num_layers)]
        self._ks = self._vs = None
        if self.quantized:
            sshape = (num_kv_heads, self.num_pages)
            self._ks = [np.zeros(sshape, np.float32)
                        for _ in range(num_layers)]
            self._vs = [np.zeros(sshape, np.float32)
                        for _ in range(num_layers)]
        self._free = list(range(self.num_pages - 1, -1, -1))

    # ---- capacity ----
    @property
    def capacity(self) -> int:
        return self.num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def arena_bytes(self) -> int:
        """Host bytes the arena occupies — the host side of the
        two-tier byte budget (``page_bytes_for`` geometry x slots)."""
        return PagedKVPool.page_bytes_for(
            self.num_layers, self.num_kv_heads, self.head_dim,
            self.page_size, self.dtype) * self.num_pages

    # ---- slots ----
    def claim(self, n: int) -> list:
        if n > len(self._free):
            raise ArenaExhausted(
                f"host arena: need {n} slots, {len(self._free)} free of "
                f"{self.num_pages}")
        return [self._free.pop() for _ in range(n)]

    def release(self, slots):
        live = set(self._free)
        for s in slots:
            if not 0 <= s < self.num_pages or s in live:
                raise ValueError(f"bad arena slot release: {s}")
            live.add(s)
        self._free.extend(slots)

    def write(self, slots, layers):
        """Store page blocks into claimed ``slots``. ``layers`` is one
        dict per layer: ``K``/``V`` ``[Hkv, len(slots), ps, d]`` (+
        ``Ks``/``Vs`` ``[Hkv, len(slots)]`` for int8 pools)."""
        idx = np.asarray(slots, np.int64)
        for li, ent in enumerate(layers):
            self._k[li][:, idx] = np.asarray(ent["K"], self.dtype)
            self._v[li][:, idx] = np.asarray(ent["V"], self.dtype)
            if self._ks is not None:
                self._ks[li][:, idx] = np.asarray(ent["Ks"], np.float32)
                self._vs[li][:, idx] = np.asarray(ent["Vs"], np.float32)

    def read(self, slots) -> list:
        """Fetch page blocks for ``slots`` (fresh numpy copies — safe to
        hand to a staging thread while the arena keeps mutating)."""
        idx = np.asarray(slots, np.int64)
        out = []
        for li in range(self.num_layers):
            ent = {"K": self._k[li][:, idx].copy(),
                   "V": self._v[li][:, idx].copy()}
            if self._ks is not None:
                ent["Ks"] = self._ks[li][:, idx].copy()
                ent["Vs"] = self._vs[li][:, idx].copy()
            out.append(ent)
        return out


class _StagedRestore:
    """One in-flight prefetch: host blocks in, device blocks out."""

    __slots__ = ("blocks", "clock", "event", "staged", "error")

    def __init__(self, blocks, clock):
        self.blocks = blocks
        self.clock = clock
        self.event = threading.Event()
        self.staged = None
        self.error = None


class KVPrefetcher:
    """Bounded background staging of arena blocks onto the device.

    The ``io/prefetch.py`` discipline, KV edition: a daemon thread
    drains a bounded queue of restore requests, ``jax.device_put``-ing
    each request's host blocks (async dispatch under PJRT — the H2D
    copy overlaps the main thread's next launch on a chip). The main
    thread owns ALL pool state; the thread touches nothing but the
    numpy blocks it was handed. ``claim`` joins the staging (bounded —
    it is one device_put batch) and reports the ISSUE round so the
    caller can classify hit vs stall deterministically on the virtual
    clock. ``enabled=False`` turns every issue into a no-op — the
    ``--no-prefetch`` injected regression: every restore then stages
    synchronously and counts as a stall.
    """

    def __init__(self, depth=4, enabled=True):
        self.depth = max(int(depth), 1)
        self.enabled = bool(enabled)
        self._items: dict = {}
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = None

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle_tpu-kv-prefetch")
            self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                it = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if it is None:
                return
            try:
                it.staged = [
                    {k: jax.device_put(v) for k, v in ent.items()}
                    for ent in it.blocks]
            except BaseException as e:   # claim falls back synchronously
                it.error = e
            finally:
                it.event.set()

    def can_issue(self, key) -> bool:
        """Would :meth:`issue` accept this key right now? (Callers use
        it to skip preparing blocks that would only be refused.)"""
        return self.enabled and key not in self._items \
            and len(self._items) < self.depth

    def issue(self, key, blocks, clock) -> bool:
        """Queue a staging request; False when disabled, already
        in flight, or the bounded queue is full (never blocks)."""
        if not self.can_issue(key):
            return False
        it = _StagedRestore(blocks, clock)
        self._items[key] = it
        self._ensure_thread()
        self._q.put(it)
        return True

    def claim(self, key):
        """Take a staged restore: ``(device_blocks, issue_clock)`` or
        ``(None, None)`` when nothing usable was staged. Waits for an
        in-flight staging (bounded: one device_put batch); a staging
        that errored degrades to a miss — the caller re-stages
        synchronously, data identical."""
        it = self._items.pop(key, None)
        if it is None:
            return None, None
        if not it.event.wait(timeout=30.0):
            return None, None
        if it.error is not None or it.staged is None:
            return None, None
        return it.staged, it.clock

    def drop(self, key):
        """Forget a staged/in-flight restore (its bytes went stale)."""
        self._items.pop(key, None)

    def close(self):
        self._stop.set()
        self._q.put(None)


class TieredKVPool(PagedKVPool):
    """Paged KV pool whose pages spill to a host-RAM arena under
    pressure and prefetch back ahead of the decode cursor.

    Everything :class:`PagedKVPool` guarantees still holds for the HBM
    tier; this class adds the second tier plus the park/spill/restore
    protocol the scheduler drives (serving/scheduler.py):

    - ``park(seq_id)`` — a preemption
      victim's exclusive unpinned pages move to the arena; the sequence
      keeps its committed length and block table (host sentinels mark
      the spilled slots) and waits at the queue front. No recompute:
      restore brings the exact bytes back.
    - ``prefetch(seq_id)`` — issue background staging for a parked
      sequence's arena blocks (the engine calls this for the queue's
      head at the END of each step — cursor-ahead).
    - ``restore_sequence(seq_id)`` — claim HBM pages and scatter the
      blocks back in at re-admission; counts a prefetch hit when the
      staging was issued a strictly earlier round, else a counted
      stall (synchronous copy, identical bytes).
    - ``spill_cold()`` — deepen the spill of already-parked sequences
      (pages that became exclusive after parking), LRU-first.

    Admission accounting is two-tier aware: watermarks and
    ``available_pages`` discount pages reclaimable by spilling, so a
    fleet never over-admits against HBM it does not have while still
    admitting up to the combined ``hbm + host`` footprint.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, *, num_pages,
                 page_size, host_pages, dtype=jnp.float32,
                 high_watermark=0.90, low_watermark=0.50,
                 pinned_page_budget=0, mesh=None, prefetch=True,
                 prefetch_depth=4, spill_seed=0):
        super().__init__(num_layers, num_kv_heads, head_dim,
                         num_pages=num_pages, page_size=page_size,
                         dtype=dtype, high_watermark=high_watermark,
                         low_watermark=low_watermark,
                         pinned_page_budget=pinned_page_budget, mesh=mesh)
        self.arena = HostKVArena(num_layers, num_kv_heads, head_dim,
                                 num_pages=host_pages,
                                 page_size=page_size, dtype=dtype)
        self.prefetcher = KVPrefetcher(depth=prefetch_depth,
                                       enabled=prefetch)
        # an abandoned pool must not leave its staging thread polling
        # forever (io/prefetch.py's finalizer discipline)
        self._prefetch_finalizer = weakref.finalize(
            self, self.prefetcher.close)
        #: virtual round clock: the engine ticks it once per step; all
        #: LRU/hit-vs-stall decisions read it, never wall-clock
        self.clock = 0
        #: seq_id -> {logical page index: arena slot} for spilled pages
        self._spilled: dict = {}
        #: parked sequences: seq_id -> (park round, seeded tie-break) —
        #: the LRU-by-last-touch key (a parked row's last touch IS the
        #: round it last ran)
        self._parked: dict = {}
        #: per-seq spill generation: bumped on every spill that touches
        #: the sequence so staged prefetches of an older page set are
        #: invalidated instead of restored stale
        self._spill_gen: dict = {}
        #: pinned chains living in the HOST tier (PR 14 warm restart
        #: lands here when HBM cannot hold them): chain -> (slots, toks)
        self._host_chains: dict = {}
        self._tie_rng = random.Random(int(spill_seed) & 0x7FFFFFFF)
        #: sequence currently being restored (its own cold pages must
        #: never be spilled to make room for its own restore)
        self._restoring = None
        #: memo for spillable_cold_pages: (state token, value)
        self._sc_cache = None
        #: lifetime tier-traffic counters (mirrored into ServingMetrics
        #: by record_step): pages spilled to the arena, restores served
        #: from a cursor-ahead staging, restores that had to stage
        #: synchronously (the counted, bounded stall), host-tier pinned
        #: chains promoted to HBM on first use
        self.spills = 0
        self.prefetch_hits = 0
        self.prefetch_stalls = 0
        self.host_chain_promotions = 0
        #: pending tier events the engine drains into the flight
        #: recorder / tracer after each step: (kind, detail) tuples
        self._events: list = []

    # ------------------------------------------------------------------
    # clock + residency queries
    # ------------------------------------------------------------------
    def tick(self):
        """Advance the virtual round clock (once per engine step)."""
        self.clock += 1

    def drain_events(self) -> list:
        ev, self._events = self._events, []
        return ev

    def is_parked(self, seq_id) -> bool:
        return seq_id in self._parked

    def fully_resident(self, seq_id) -> bool:
        return not self._spilled.get(seq_id)

    def spilled_page_count(self, seq_id) -> int:
        return len(self._spilled.get(seq_id, ()))

    @property
    def host_pages_used(self) -> int:
        return self.arena.used_pages

    @property
    def resident_fraction(self) -> float:
        """Fraction of live KV pages (sequences + pins + host chains)
        that are HBM-resident; 1.0 for an empty or all-resident pool."""
        total = self.used_pages + self.arena.used_pages
        return self.used_pages / total if total else 1.0

    @property
    def total_capacity(self) -> int:
        """Allocatable pages across BOTH tiers — what bounds the live
        context of the whole engine (vs ``capacity``, which bounds one
        launch's residency)."""
        return self.capacity + self.arena.capacity

    # ---- two-tier byte accounting (the admission-bugfix satellite) ----
    @property
    def host_bytes(self) -> int:
        return self.arena.arena_bytes

    def tier_bytes(self) -> tuple:
        """(hbm_bytes, host_bytes) — the two budgets an operator sizes
        independently (``pool_bytes`` is the inherited HBM-tier size;
        ``pages_for_byte_budget`` applies per tier)."""
        return (self.pool_bytes, self.host_bytes)

    @classmethod
    def pages_for_byte_budgets(cls, hbm_byte_budget, host_byte_budget,
                               num_layers, num_kv_heads, head_dim,
                               page_size, dtype=jnp.float32) -> tuple:
        """Largest (hbm_pages, host_pages) fitting the per-tier byte
        budgets — the two-tier edition of ``pages_for_byte_budget``
        (one budget must never be sized against the other's RAM)."""
        return (cls.pages_for_byte_budget(hbm_byte_budget, num_layers,
                                          num_kv_heads, head_dim,
                                          page_size, dtype),
                cls.pages_for_byte_budget(host_byte_budget, num_layers,
                                          num_kv_heads, head_dim,
                                          page_size, dtype))

    # ------------------------------------------------------------------
    # spill side
    # ------------------------------------------------------------------
    def _spillable(self, seq_id) -> list:
        """Logical page indices of ``seq_id`` that may spill: resident,
        exclusively owned (refcount 1 — never a CoW-shared page another
        reader may touch), and unpinned (never a prefix chain's page)."""
        out = []
        for i, p in enumerate(self._tables.get(seq_id, ())):
            if p >= 0 and self._refcounts[p] == 1 \
                    and p not in self._pin_counts:
                out.append(i)
        return out

    def can_park(self, seq_id) -> bool:
        """True when parking would actually relieve pressure: the
        sequence has spillable pages and the arena can hold them all
        (all-or-nothing — a half-spilled park frees too little to be
        worth preferring over recompute preemption)."""
        n = len(self._spillable(seq_id))
        return n > 0 and n <= self.arena.free_pages

    def _spill_pages(self, seq_id, logicals) -> int:
        if not logicals:
            return 0
        table = self._tables[seq_id]
        pages = [table[i] for i in logicals]
        slots = self.arena.claim(len(logicals))
        idx = jnp.asarray(pages, jnp.int32)
        layers = []
        for li, (K, V) in enumerate(self.kv):
            ent = {"K": np.asarray(K[:, idx]), "V": np.asarray(V[:, idx])}
            if self.kv_scales is not None:
                Ks, Vs = self.kv_scales[li]
                ent["Ks"] = np.asarray(Ks[:, idx])
                ent["Vs"] = np.asarray(Vs[:, idx])
            layers.append(ent)
        self.arena.write(slots, layers)
        for i, s in zip(logicals, slots):
            table[i] = -(s + 1)
        self._spilled.setdefault(seq_id, {}).update(zip(logicals, slots))
        # the page set changed: any staged prefetch of the OLD set is
        # stale — bump the generation so restore never consumes it
        gen = self._spill_gen.get(seq_id, 0)
        self.prefetcher.drop((seq_id, gen))
        self._spill_gen[seq_id] = gen + 1
        # recycle the HBM pages (refcount 1 -> 0; int8 scale columns of
        # the recycled pages reset, their saved values travel with the
        # arena blocks)
        self._release_pages(pages)
        self.spills += len(pages)
        return len(pages)

    def park(self, seq_id):
        """Mark a sequence parked at the current round (its last touch)
        and spill every spillable page. The scheduler keeps the
        Sequence WAITING at the queue front; its committed length and
        block table survive — restore is bit-exact, no recompute."""
        self._parked[seq_id] = (self.clock, self._tie_rng.random())
        return self._spill_pages(seq_id, self._spillable(seq_id))

    def _ensure_free(self, n: int, what: str):
        """Two-tier pressure relief UNDER every page claim: deepen the
        cold spill of parked sequences (never the one being restored)
        before the base class falls back to pin eviction — so extends,
        CoW claims and restores reach the host tier's headroom without
        every caller growing its own retry loop."""
        while n > len(self._free) + self.evictable_pages:
            if self.spill_cold(exclude=self._restoring) == 0:
                break
        super()._ensure_free(n, what)

    def _parked_lru(self) -> list:
        """Parked seq ids, coldest first: ordered by (park round,
        seeded tie-break) — deterministic per seed, wall-clock-free."""
        return sorted(self._parked, key=lambda s: self._parked[s])

    def spill_cold(self, exclude=None) -> int:
        """Deepen the spill: take the coldest parked sequence that
        still holds spillable resident pages (pages that became
        exclusive after parking, e.g. a sharer left) and spill them.
        ``exclude`` names a sequence that must NOT be deepened — the
        restore path passes itself (self-spilling frees no net HBM
        and would grow the very page set being restored). Returns
        pages freed (0 = nothing left to spill)."""
        for sid in self._parked_lru():
            if sid == exclude:
                continue
            logicals = self._spillable(sid)
            if not logicals:
                continue
            n = min(len(logicals), self.arena.free_pages)
            if n <= 0:
                return 0
            return self._spill_pages(sid, logicals[:n])
        return 0

    @property
    def spillable_cold_pages(self) -> int:
        """Resident pages reclaimable by deepening the spill of parked
        sequences, bounded by the arena's free slots — the second-tier
        term in the admission watermark math.

        Memoized on a coarse state token: the full scan is
        O(parked x table length) and the watermark/admission path reads
        this several times per step. The token misses pure refcount
        flips (a fork de-/re-sharing a parked page), so the value can
        be one transition stale — benign by design: admission checks
        here are advisory, and every claim path defers cleanly on a
        real shortfall (``_ensure_free`` re-derives truth when it
        actually spills)."""
        token = (self.clock, self.spills, self.used_pages,
                 len(self._free), len(self._parked), self.cow_copies,
                 self.pin_evictions, len(self._pins))
        if self._sc_cache is not None and self._sc_cache[0] == token:
            return self._sc_cache[1]
        n = sum(len(self._spillable(sid)) for sid in self._parked)
        val = min(n, self.arena.free_pages)
        self._sc_cache = (token, val)
        return val

    def restore_headroom(self, seq_id) -> int:
        """Pages claimable toward RESTORING ``seq_id``: free +
        pin-evictable + cold pages of the OTHER parked sequences.
        The candidate's own cold pages are excluded — spilling the
        sequence being restored frees no net HBM (admission must
        defer, not thrash)."""
        other = sum(len(self._spillable(s)) for s in self._parked
                    if s != seq_id)
        return len(self._free) + self.evictable_pages \
            + min(other, self.arena.free_pages)

    # ---- two-tier admission accounting ----
    def _demand_pages(self) -> int:
        return self.used_pages - self.evictable_pages \
            - self.spillable_cold_pages

    def above_high_watermark(self, extra_pages=0) -> bool:
        return (self._demand_pages() + extra_pages) / self.capacity \
            > self.high_watermark

    def below_low_watermark(self) -> bool:
        return self._demand_pages() / self.capacity < self.low_watermark

    @property
    def available_pages(self) -> int:
        return super().available_pages + self.spillable_cold_pages

    # ------------------------------------------------------------------
    # prefetch + restore side
    # ------------------------------------------------------------------
    def _restore_order(self, seq_id):
        sp = self._spilled[seq_id]
        logicals = sorted(sp)
        return logicals, [sp[i] for i in logicals]

    def prefetch(self, seq_id) -> bool:
        """Issue cursor-ahead staging for a parked sequence's arena
        blocks. Host-side reads happen HERE (main thread owns the
        arena); the staging thread only device_puts the copies. No-op
        when the sequence has nothing spilled, staging is disabled, or
        the bounded queue is full."""
        if not self._spilled.get(seq_id):
            return False
        key = (seq_id, self._spill_gen.get(seq_id, 0))
        if not self.prefetcher.can_issue(key):
            return False
        _, slots = self._restore_order(seq_id)
        return self.prefetcher.issue(key, self.arena.read(slots),
                                     self.clock)

    def restore_sequence(self, seq_id) -> int:
        """Bring a parked sequence fully HBM-resident for re-admission:
        claim pool pages (deepening the cold spill first if free +
        pin-evictable pages fall short), scatter the arena blocks back
        in, and rewrite the block table. Counts a prefetch HIT when the
        blocks were staged a strictly earlier round (the background
        thread had a full step to overlap), else a counted bounded
        STALL — the copy then happens synchronously and the restored
        bytes are identical either way. Returns pages restored."""
        sp = self._spilled.get(seq_id)
        if not sp:
            self._parked.pop(seq_id, None)
            return 0
        n = len(sp)
        logicals, slots = self._restore_order(seq_id)
        # claim BEFORE consuming the staged prefetch: _ensure_free may
        # deepen OTHER parked sequences' spill (never this one —
        # _restoring guards it: self-spilling frees no net HBM and
        # would grow the page set mid-restore), and a shortfall raises
        # PoolExhausted with tables/spill maps untouched (at worst some
        # LRU pins were evicted — cache, not state) and the staging
        # still intact for the retry (admission gates on
        # restore_headroom, so this is the defensive backstop)
        self._restoring = seq_id
        try:
            pages = self._claim(n, f"restore parked sequence {seq_id!r} "
                                   f"({n} pages)")
        finally:
            self._restoring = None
        key = (seq_id, self._spill_gen.get(seq_id, 0))
        staged, issued = self.prefetcher.claim(key)
        if staged is not None and issued < self.clock:
            self.prefetch_hits += 1
            blocks = staged
        else:
            # the prefetch lost the race to the cursor (or was never
            # issued / went stale): stage synchronously, count it
            self.prefetch_stalls += 1
            self._events.append(("kv_prefetch_stall",
                                 {"request": seq_id, "pages": n}))
            blocks = staged if staged is not None \
                else [{k: jnp.asarray(v) for k, v in ent.items()}
                      for ent in self.arena.read(slots)]
        idx = jnp.asarray(pages, jnp.int32)
        self.kv = [(K.at[:, idx].set(jnp.asarray(ent["K"], self.dtype)),
                    V.at[:, idx].set(jnp.asarray(ent["V"], self.dtype)))
                   for (K, V), ent in zip(self.kv, blocks)]
        if self.kv_scales is not None:
            self.kv_scales = [
                (Ks.at[:, idx].set(jnp.asarray(ent["Ks"], jnp.float32)),
                 Vs.at[:, idx].set(jnp.asarray(ent["Vs"], jnp.float32)))
                for (Ks, Vs), ent in zip(self.kv_scales, blocks)]
        self._repin()
        table = self._tables[seq_id]
        for i, p in zip(logicals, pages):
            table[i] = p
        self.arena.release(slots)
        del self._spilled[seq_id]
        self._parked.pop(seq_id, None)
        self._spill_gen.pop(seq_id, None)
        return n

    # ------------------------------------------------------------------
    # lifecycle overrides (host tier cleanup + residency guards)
    # ------------------------------------------------------------------
    def free(self, seq_id) -> int:
        sp = self._spilled.pop(seq_id, None)
        self._parked.pop(seq_id, None)
        gen = self._spill_gen.pop(seq_id, None)
        if gen is not None:
            self.prefetcher.drop((seq_id, gen))
        if sp:
            self.arena.release(list(sp.values()))
        pages = self._tables.pop(seq_id)
        self._lens.pop(seq_id, None)
        return self._release_pages([p for p in pages if p >= 0])

    def padded_block_table(self, seq_id, pages: int) -> list:
        # the launch-side residency guard: a host sentinel reaching a
        # block table would make the kernel read recycled HBM bytes —
        # fail loudly instead (restore_sequence must run first)
        table = self._tables[seq_id]
        bad = [p for p in table if p < 0]
        if bad:
            self._invariant_fail(
                f"launch over non-resident sequence {seq_id!r}: "
                f"{len(bad)} spilled pages in its block table", bad)
        return super().padded_block_table(seq_id, pages)

    def fork(self, seq_id, parent_id, num_tokens=None):
        # residency gate BEFORE any bookkeeping: a host sentinel is a
        # negative "page id" and would silently corrupt refcounts if it
        # reached the base fork — callers must only fork fully-resident
        # donor prefixes (the engine's prefix probe checks first)
        parent = self._tables[parent_id]
        if num_tokens is None:
            num_tokens = (self._lens[parent_id] // self.page_size) \
                * self.page_size
        bad = [p for p in parent[:self.pages_for(num_tokens)] if p < 0]
        if bad:
            raise PoolExhausted(
                f"fork of {parent_id!r}: donor prefix is not fully "
                f"resident ({len(bad)} spilled pages)")
        return super().fork(seq_id, parent_id, num_tokens)

    # ------------------------------------------------------------------
    # pinned chains: restore into either tier (PR 14 warm restart)
    # ------------------------------------------------------------------
    def restore_pinned_chain(self, chain_id, num_tokens, layers) -> bool:
        """HBM while it fits WITHOUT eviction; overflow lands in the
        HOST tier instead of evicting another chain (pre-tiering, a
        restart into a smaller HBM pool silently dropped the colder
        chains — now the whole warm cache survives). A host-tier chain
        promotes to HBM (and becomes a real pin, evicting colder pins
        if it must) on its first ``fork_pinned``."""
        if num_tokens % self.page_size != 0:
            raise ValueError(
                f"restored chains must be page-aligned: {num_tokens} "
                f"tokens over page_size {self.page_size}")
        n_pages = num_tokens // self.page_size
        if n_pages < 1 or n_pages > self.pinned_page_budget:
            return False
        if n_pages <= len(self._free) and \
                self.pinned_pages + n_pages <= self.pinned_page_budget:
            return super().restore_pinned_chain(chain_id, num_tokens,
                                                layers)
        if n_pages > self.arena.free_pages:
            # no arena room either: the pre-tiering evict-to-fit path
            # is still better than dropping the chain outright
            return super().restore_pinned_chain(chain_id, num_tokens,
                                                layers)
        want = (self.num_kv_heads, n_pages, self.page_size, self.head_dim)
        for li, ent in enumerate(layers):
            if tuple(np.asarray(ent["K"]).shape) != want:
                raise ValueError(
                    f"restored chain layer {li}: block shape "
                    f"{tuple(np.asarray(ent['K']).shape)} != pool {want}")
        if chain_id in self._host_chains:
            self.arena.release(self._host_chains.pop(chain_id)[0])
        slots = self.arena.claim(n_pages)
        self.arena.write(slots, [
            {k: ent[k] for k in
             (("K", "V", "Ks", "Vs") if self.quantized else ("K", "V"))}
            for ent in layers])
        self._host_chains[chain_id] = (slots, num_tokens)
        return True

    def is_pinned(self, chain_id) -> bool:
        return super().is_pinned(chain_id) or chain_id in self._host_chains

    def _promote_chain(self, chain_id) -> bool:
        """Move a host-tier chain into HBM as a real pin (first-use
        promotion). False when HBM still cannot hold it — the chain
        stays in the host tier, the probe treats it as a miss."""
        slots, num_tokens = self._host_chains[chain_id]
        layers = self.arena.read(slots)
        if not super().restore_pinned_chain(chain_id, num_tokens, layers):
            return False
        self.arena.release(slots)
        del self._host_chains[chain_id]
        self.host_chain_promotions += 1
        self._events.append(("kv_chain_promotion",
                             {"chain_pages": num_tokens // self.page_size}))
        return True

    def fork_pinned(self, seq_id, chain_id, num_tokens: int) -> list:
        if chain_id in self._host_chains:
            if not self._promote_chain(chain_id):
                raise PoolExhausted(
                    f"pinned chain {chain_id!r} cannot promote from the "
                    f"host tier ({self._host_chains[chain_id][1]} tokens)")
        return super().fork_pinned(seq_id, chain_id, num_tokens)

    def unpin(self, chain_id) -> int:
        if chain_id in self._host_chains:
            self.arena.release(self._host_chains.pop(chain_id)[0])
            return 0
        return super().unpin(chain_id)

    def export_pinned(self) -> list:
        """HBM pins (device reads) + host-tier chains (arena reads) —
        a save must persist the whole warm cache, whichever tier holds
        each chain."""
        out = super().export_pinned()
        for cid, (slots, num_tokens) in self._host_chains.items():
            out.append({"chain_id": cid, "num_tokens": num_tokens,
                        "layers": self.arena.read(slots)})
        return out

    def export_chain(self, chain_id) -> list:
        if chain_id in self._host_chains:
            return self.arena.read(self._host_chains[chain_id][0])
        return super().export_chain(chain_id)

    # ------------------------------------------------------------------
    # disaggregated serving: adopt transferred pages via the host arena
    # ------------------------------------------------------------------
    def adopt_sequence(self, seq_id, num_tokens, layers) -> list:
        """Two-tier adoption (the fabric's landing pad): the transferred
        blocks stage into the HOST ARENA and the sequence lands PARKED —
        a host-sentinel block table over fresh arena slots — so
        re-admission rides the exact machinery parked sequences already
        use (cursor-ahead :class:`KVPrefetcher` staging, hit-vs-stall
        accounting, ``restore_sequence``'s scatter). No HBM is claimed
        until the scheduler actually admits the row. Falls back to the
        base direct-to-HBM adoption when the arena cannot hold the
        pages (better resident than refused)."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already has an allocation")
        if len(layers) != self.num_layers:
            raise ValueError(
                f"adopted sequence has {len(layers)} layers, pool has "
                f"{self.num_layers}")
        n_pages = self.pages_for(num_tokens)
        if n_pages > self.arena.free_pages:
            return super().adopt_sequence(seq_id, num_tokens, layers)
        want = (self.num_kv_heads, n_pages, self.page_size, self.head_dim)
        for li, ent in enumerate(layers):
            if tuple(np.asarray(ent["K"]).shape) != want:
                raise ValueError(
                    f"adopted sequence layer {li}: block shape "
                    f"{tuple(np.asarray(ent['K']).shape)} != pool {want}")
        slots = self.arena.claim(n_pages)
        self.arena.write(slots, layers)
        self._tables[seq_id] = [-(s + 1) for s in slots]
        self._lens[seq_id] = num_tokens
        self._spilled[seq_id] = dict(enumerate(slots))
        self._parked[seq_id] = (self.clock, self._tie_rng.random())
        return list(self._tables[seq_id])

    # ------------------------------------------------------------------
    # invariants: a page lives in exactly one tier
    # ------------------------------------------------------------------
    def _resident_table(self, t):
        return [p for p in t if p >= 0]

    def snapshot(self, offending_pages=()) -> dict:
        snap = super().snapshot(offending_pages)
        snap["host_pages_used"] = self.arena.used_pages
        snap["host_capacity"] = self.arena.capacity
        snap["parked"] = sorted(self._parked,
                                key=lambda s: self._parked[s])
        snap["spilled_pages"] = {s: len(m)
                                 for s, m in self._spilled.items()}
        snap["host_chains"] = len(self._host_chains)
        return snap

    def check_invariants(self):
        used_slots: dict = {}
        for sid, t in self._tables.items():
            sp = self._spilled.get(sid, {})
            for i, p in enumerate(t):
                if p < 0:
                    slot = -(p + 1)
                    if sp.get(i) != slot:
                        self._invariant_fail(
                            f"table {sid!r} logical page {i} names arena "
                            f"slot {slot} but the spill map says "
                            f"{sp.get(i)}", [p])
                    if slot in used_slots:
                        self._invariant_fail(
                            f"arena slot {slot} mapped twice "
                            f"({used_slots[slot]} and {sid!r}) — a page "
                            f"must live in exactly one tier", [p])
                    used_slots[slot] = sid
            if len(sp) != sum(1 for p in t if p < 0):
                self._invariant_fail(
                    f"spill map of {sid!r} has {len(sp)} entries but its "
                    f"table has {sum(1 for p in t if p < 0)} host "
                    f"sentinels", [])
        for sid in self._spilled:
            if sid not in self._tables:
                self._invariant_fail(
                    f"spill map names unknown sequence {sid!r}", [])
        for sid in self._parked:
            if sid not in self._tables:
                self._invariant_fail(
                    f"parked set names unknown sequence {sid!r}", [])
        for cid, (slots, _n) in self._host_chains.items():
            for s in slots:
                if s in used_slots:
                    self._invariant_fail(
                        f"arena slot {s} held by host chain {cid!r} AND "
                        f"{used_slots[s]!r}", [])
                used_slots[s] = cid
        free = set(self.arena._free)
        if len(free) != len(self.arena._free):
            self._invariant_fail("arena free list has duplicates", [])
        if free & set(used_slots):
            self._invariant_fail(
                f"arena slots both used and free: "
                f"{sorted(free & set(used_slots))[:8]}", [])
        if len(used_slots) + len(free) != self.arena.capacity:
            self._invariant_fail(
                f"arena accounting leak: {len(used_slots)} used + "
                f"{len(free)} free != capacity {self.arena.capacity}", [])
        # pinned pages are never spilled: every pin-counted page must be
        # a resident pool page (sentinels never enter _pin_counts — this
        # guards against a future spill path forgetting the exclusion)
        bad_pins = [p for p in self._pin_counts if p < 0]
        if bad_pins:
            self._invariant_fail("pinned page spilled to the host tier",
                                 bad_pins)
        return super().check_invariants()


__all__ = ["ArenaExhausted", "HostKVArena", "KVPrefetcher",
           "TieredKVPool"]
