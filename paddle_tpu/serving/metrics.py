"""Serving metrics: counters/gauges for the engine, scheduler, and pool.

Two consumers:
- ``snapshot()`` — a plain dict for bench.py (``serving_tokens_per_s``,
  ``kv_page_utilization``, ``decode_compiles`` ride the bench artifact)
  and for tests/operators polling the engine;
- the profiler timeline — each ``record_step`` emits instant events
  through the same native recorder paddle_tpu.profiler drains, so serving
  gauges land on the chrome-trace/protobuf timeline next to op spans when
  a Profiler is recording.
"""
from __future__ import annotations

import time
from collections import deque

from ..core import native as _nv


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0.0

    def set(self, v):
        self.value = v


class ServingMetrics:
    COUNTERS = ("requests_added", "rejected_requests", "tokens_generated",
                "prefills", "prefill_chunks", "decode_steps", "preemptions",
                "shed_requests", "cancelled_requests", "finished_requests",
                "decode_compiles", "cow_copies", "prefix_cache_hits",
                "prefix_cache_misses",
                # burst/megakernel forensics: jitted launches the host
                # issued (the dispatch gate's numerator), on-device
                # generation bursts, and prefix-cache hits served by a
                # PINNED chain after its last sequence sharer left
                "host_dispatches", "burst_launches", "pinned_prefix_hits")
    GAUGES = ("queue_depth", "running_seqs", "waiting_seqs",
              "page_utilization", "tokens_per_s", "ragged_pad_fraction",
              "shared_page_fraction", "pinned_pages")

    #: tokens_per_s is the rate over this trailing window, not a lifetime
    #: average — a lifetime average decays toward zero across idle gaps
    RATE_WINDOW_S = 60.0

    def __init__(self, now_fn=time.monotonic):
        self._now = now_fn
        self._t0 = now_fn()
        self._rate_samples = deque([(self._t0, 0)])   # (t, tokens_total)
        for c in self.COUNTERS:
            setattr(self, c, Counter(c))
        for g in self.GAUGES:
            setattr(self, g, Gauge(g))

    def record_step(self, scheduler, pool):
        """Refresh gauges from live state; emit profiler instants."""
        self.queue_depth.set(scheduler.queue_depth())
        self.running_seqs.set(len(scheduler.running))
        self.waiting_seqs.set(len(scheduler.waiting))
        self.page_utilization.set(pool.utilization)
        self.shared_page_fraction.set(
            getattr(pool, "shared_page_fraction", 0.0))
        self.pinned_pages.set(getattr(pool, "pinned_pages", 0))
        now = self._now()
        self._rate_samples.append((now, self.tokens_generated.value))
        while len(self._rate_samples) > 2 and \
                now - self._rate_samples[0][0] > self.RATE_WINDOW_S:
            self._rate_samples.popleft()
        t_old, tok_old = self._rate_samples[0]
        self.tokens_per_s.set(
            (self.tokens_generated.value - tok_old) / max(now - t_old, 1e-9))
        if _nv.prof_enabled():
            for g in self.GAUGES:
                v = getattr(self, g).value
                _nv.prof_instant(f"serving.{g}={v:.3f}", 3)

    def snapshot(self) -> dict:
        out = {c: getattr(self, c).value for c in self.COUNTERS}
        out.update({g: getattr(self, g).value for g in self.GAUGES})
        out["uptime_s"] = self._now() - self._t0
        return out


__all__ = ["Counter", "Gauge", "ServingMetrics"]
