"""Serving metrics: counters/gauges/histograms for the engine, scheduler,
and pool.

Two consumers:
- ``snapshot()`` — a plain dict for bench.py (``serving_tokens_per_s``,
  ``kv_page_utilization``, ``decode_compiles`` ride the bench artifact)
  and for tests/operators polling the engine;
- the profiler timeline — each ``record_step`` emits instant events
  through the same native recorder paddle_tpu.profiler drains, so serving
  gauges land on the chrome-trace/protobuf timeline next to op spans when
  a Profiler is recording.

Latency observability (the loadgen substrate, docs/BENCH.md): every
FINISHED request records its TTFT (arrival -> first generated token),
TPOT (mean inter-token time after the first) and e2e latency into
bounded-reservoir :class:`Histogram`\\ s, so p50/p90/p99 exist on any
long-running engine without an external harness. Queue starvation is
observable through the ``queue_age_p99_s`` / ``max_queue_wait_s`` gauges
(per-request enqueue timestamps come from the scheduler's ``now_fn``, so
they are virtual-clock-accurate under paddle_tpu.loadgen).
"""
from __future__ import annotations

import random
import time
import zlib
from collections import deque

from ..core import native as _nv


def percentile_of(values, q):
    """Deterministic linear-interpolation percentile of a value list
    (numpy's default method, dependency-free). None on empty input."""
    if not values:
        return None
    s = sorted(float(v) for v in values)
    n = len(s)
    if n == 1:
        return s[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    if lo >= n - 1:
        return s[-1]
    frac = pos - lo
    return s[lo] + (s[lo + 1] - s[lo]) * frac


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    """Point-in-time value, stamped with its last-update time.

    ``updated_at`` (the caller's ``now_fn`` time base — the engine's
    virtual clock under loadgen) is what separates "this replica's queue
    is empty" from "this replica stopped reporting": a gauge that was
    last set before a replica died keeps its final value forever, and
    without the stamp a fleet health read cannot tell. ``age_s(now)``
    is None until the first ``set`` — a never-set gauge has no age, it
    has no data."""

    __slots__ = ("name", "value", "updated_at", "_now")

    def __init__(self, name, now_fn=None):
        self.name = name
        self.value = 0.0
        #: time of the last set() on the owner's now_fn clock; None
        #: until the gauge is first written
        self.updated_at = None
        self._now = now_fn

    def set(self, v):
        self.value = v
        if self._now is not None:
            self.updated_at = self._now()

    def age_s(self, now) -> float | None:
        """Seconds since the last set (None if never set) — the
        staleness signal snapshots and the telemetry scraper key off."""
        return None if self.updated_at is None else now - self.updated_at


class Histogram:
    """Bounded-reservoir histogram with percentile queries.

    Memory is capped at ``max_samples`` observations (classic reservoir
    sampling beyond that), so a long-running server's latency histograms
    never grow with traffic; below the cap the percentiles are exact.
    The reservoir's replacement stream is seeded from the histogram's
    NAME (crc32 — stable across processes, unlike ``hash``), so two runs
    observing identical value streams report bit-identical percentiles —
    the loadgen determinism gate (tests/test_loadgen.py) depends on it.
    """

    __slots__ = ("name", "count", "total", "min", "max", "max_samples",
                 "_samples", "_rng")

    def __init__(self, name, max_samples=2048):
        if max_samples < 1:
            raise ValueError("max_samples must be >= 1")
        self.name = name
        self.max_samples = int(max_samples)
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples = []
        self._rng = random.Random(zlib.crc32(str(name).encode("utf-8")))

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self._samples) < self.max_samples:
            self._samples.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.max_samples:
                self._samples[j] = v

    @property
    def mean(self):
        return self.total / self.count if self.count else None

    def percentile(self, q):
        """q in [0, 100]; None when nothing was observed — an empty
        reservoir has no percentiles, never a fabricated 0
        (tests/test_telemetry.py pins the contract, merge included)."""
        return percentile_of(self._samples, q)

    def summary(self) -> dict:
        """{count, mean, min, max, p50, p90, p99} — Nones when empty."""
        return {"count": self.count, "mean": self.mean,
                "min": self.min, "max": self.max,
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}

    def sample_state(self) -> dict:
        """Plain-data copy of the histogram's observable state —
        what the telemetry scraper retains per replica so a crashed
        engine's latency population survives into fleet percentiles
        (the counter-carry discipline, histogram edition)."""
        return {"count": self.count, "total": self.total,
                "min": self.min, "max": self.max,
                "samples": list(self._samples)}

    @classmethod
    def merge(cls, sources, *, name="merged", max_samples=None):
        """Deterministically merge histograms (or ``sample_state()``
        dicts) into one — the fleet-percentile primitive: each
        replica's bounded reservoir contributes its retained samples IN
        CALLER ORDER through the merged histogram's own crc32-name-
        seeded reservoir, so two merges of the same sources are
        bit-identical; count/total/min/max are then corrected to the
        TRUE aggregates (they never sample). Below every reservoir's
        cap the merged percentiles are exact over the pooled
        population; above it they are reservoir-approximate, like any
        single histogram's. Empty sources merge to an empty histogram
        whose percentiles are None — never a fabricated 0."""
        if max_samples is None:
            caps = [s.max_samples for s in sources
                    if isinstance(s, Histogram)]
            max_samples = max(caps) if caps else 2048
        out = cls(name, max_samples=max_samples)
        count = 0
        total = 0.0
        mn = mx = None
        for src in sources:
            st = src.sample_state() if isinstance(src, Histogram) else src
            for v in st["samples"]:
                out.observe(v)
            count += st["count"]
            total += st["total"]
            if st["min"] is not None:
                mn = st["min"] if mn is None else min(mn, st["min"])
                mx = st["max"] if mx is None else max(mx, st["max"])
        # observe() tracked the RETAINED samples; the aggregate stats
        # must reflect every observation the sources ever made
        out.count = count
        out.total = total
        out.min = mn
        out.max = mx
        return out


class ServingMetrics:
    COUNTERS = ("requests_added", "rejected_requests", "tokens_generated",
                "prefills", "prefill_chunks", "decode_steps", "preemptions",
                "shed_requests", "cancelled_requests", "finished_requests",
                "decode_compiles", "cow_copies", "prefix_cache_hits",
                "prefix_cache_misses",
                # burst/megakernel forensics: jitted launches the host
                # issued (the dispatch gate's numerator), on-device
                # generation bursts, and prefix-cache hits served by a
                # PINNED chain after its last sequence sharer left
                "host_dispatches", "burst_launches", "pinned_prefix_hits",
                # fused ragged prefill (kernels/prefill_megakernel.py):
                # steps that served >= 1 prefill-chunk row — the ragged
                # step is ONE executable, so each such step is ONE
                # launch covering every chunk in it;
                # prefill_launches / prefill_chunks is the
                # launches-per-chunk headline the fused path collapses
                "prefill_launches",
                # speculative decoding (serving/spec_decode.py): draft
                # candidates offered for verification, candidates the
                # rejection sampler accepted, verification rounds that
                # rolled a KV tail back (>= 1 candidate rejected), and
                # spec rounds run
                "spec_drafted_tokens", "spec_accepted_tokens",
                "spec_rollbacks", "spec_rounds",
                # rounds demoted to ordinary decode because the DRAFT
                # pool could not hold them (under-sized draft_num_pages)
                "spec_draft_fallbacks",
                # robustness (PR 11): running/waiting requests aborted at
                # a step boundary because their e2e deadline passed
                # (finish_reason "deadline_exceeded"), ragged rows whose
                # logits came back NaN/Inf (the in-graph isfinite guard —
                # each aborts its request instead of sampling garbage),
                # and graceful-degradation ladder transitions (rungs
                # engaged under sustained pressure / restored after it
                # clears — serving/cluster.DegradationLadder)
                "deadline_aborts", "nonfinite_rows",
                "degradation_escalations", "degradation_restorations",
                # observability (PR 12): flight-recorder post-mortem
                # dumps taken (InvariantViolation / nonfinite abort /
                # replica crash auto-dumps + any operator-requested one)
                "flight_dumps",
                # crash-consistent persistence (io/persist.py): degraded
                # restores — a corrupt/unusable persisted artifact fell
                # back to an older version or to a cold start instead of
                # loading bad bytes; pinned prefix chains warm-reloaded
                # from the store at engine construction; pin-set
                # snapshots persisted (the write-ahead warm-start path)
                "restore_fallbacks", "prefix_chains_restored",
                "prefix_store_saves",
                # two-tier KV cache (serving/kv_tier.py): pages spilled
                # to the host-RAM arena (cold pages of parked
                # sequences), parked-sequence restores served from a
                # cursor-ahead background staging, and restores the
                # prefetcher did NOT stage a full round ahead — the
                # counted, bounded stall (the copy runs synchronously;
                # tokens stay bit-identical, only overlap is lost)
                "kv_spills", "kv_prefetch_hits", "kv_prefetch_stalls",
                # disaggregated serving (serving/fabric.py): KV pages
                # landed on THIS replica over the fabric (decode side of
                # a prefill -> decode handoff), handoffs the bounded
                # fabric refused this round (issue retried next round —
                # the counted backpressure signal), and prefix-cache
                # hits served from the FLEET store (pages prefilled on
                # another replica, faulted in content-addressed)
                "kv_pages_transferred", "transfer_stalls",
                "fleet_prefix_hits",
                # multi-tenant economy (paddle_tpu.tenancy): waiting
                # requests shed because their tenant's token bucket
                # could not fund them (reason "quota_exceeded"), LoRA
                # adapters hot-published into the registry, slots
                # reclaimed by LRU eviction, evictions REFUSED because
                # in-flight requests still wear the adapter (the
                # structured AdapterInUse path — never a silent slot-0
                # fallback), adapters warm-reloaded from the store at
                # engine construction, and adapter-store snapshots
                # persisted
                "quota_shed_requests", "adapter_hot_adds",
                "adapter_evictions", "adapter_evict_refusals",
                "adapter_restores", "adapter_store_saves")
    GAUGES = ("queue_depth", "running_seqs", "waiting_seqs",
              "page_utilization", "tokens_per_s", "ragged_pad_fraction",
              "shared_page_fraction", "pinned_pages",
              # lifetime draft acceptance rate (accepted / drafted) —
              # the headline spec-decoding health signal: target steps
              # per committed token ~= 1 / (1 + accept_rate * k)
              "spec_accept_rate",
              # starvation observability: age of the oldest / p99 waiting
              # request (seconds since it was (re-)enqueued, scheduler
              # now_fn time base) — a climbing max_queue_wait_s under
              # steady load is head-of-line blocking made visible
              "queue_age_p99_s", "max_queue_wait_s",
              # current graceful-degradation rung (0 = full service;
              # each rung sheds one optional capability in order)
              "degradation_level",
              # two-tier KV cache: host-arena slots in use (sequences +
              # host-tier pinned chains) and the fraction of live KV
              # pages that are HBM-resident (1.0 for single-tier pools
              # — there is no second tier to be non-resident in)
              "kv_host_pages_used", "kv_resident_fraction",
              # multi-tenant LoRA: adapter registry slots in use (slot 0
              # — the base model — never counts); 0 for engines without
              # a registry
              "adapter_slots_used")
    #: per-finished-request latency distributions (seconds): TTFT =
    #: arrival -> first generated token, TPOT = mean inter-token after
    #: the first, e2e = arrival -> finalization
    HISTOGRAMS = ("ttft_s", "tpot_s", "e2e_s")

    #: tokens_per_s is the rate over this trailing window, not a lifetime
    #: average — a lifetime average decays toward zero across idle gaps
    RATE_WINDOW_S = 60.0

    def __init__(self, now_fn=time.monotonic, *, stale_after_s=None):
        self._now = now_fn
        self._t0 = now_fn()
        #: gauge-staleness horizon: a gauge last set more than this many
        #: seconds ago (or never set) is MARKED in snapshot() — its
        #: value is reported as null and its name listed under
        #: ``stale_gauges`` — instead of silently reading as current.
        #: None (the default) disables marking; the telemetry scraper
        #: applies its own horizon either way.
        self.stale_after_s = stale_after_s
        self._rate_samples = deque([(self._t0, 0)])   # (t, tokens_total)
        for c in self.COUNTERS:
            setattr(self, c, Counter(c))
        for g in self.GAUGES:
            setattr(self, g, Gauge(g, now_fn=now_fn))
        for h in self.HISTOGRAMS:
            setattr(self, h, Histogram(h))

    def record_request_end(self, *, arrival, first_token_at, finished_at,
                           n_tokens):
        """Observe one FINISHED request's latencies into the histograms.
        Called by the engine at finalization; shed/cancelled/aborted
        requests never get here (their "latency" is not a service time).
        """
        self.e2e_s.observe(finished_at - arrival)
        if first_token_at is not None:
            self.ttft_s.observe(first_token_at - arrival)
            if n_tokens > 1:
                self.tpot_s.observe(
                    (finished_at - first_token_at) / (n_tokens - 1))

    def record_step(self, scheduler, pool):
        """Refresh gauges from live state; emit profiler instants."""
        self.queue_depth.set(scheduler.queue_depth())
        self.running_seqs.set(len(scheduler.running))
        self.waiting_seqs.set(len(scheduler.waiting))
        self.page_utilization.set(pool.utilization)
        self.shared_page_fraction.set(
            getattr(pool, "shared_page_fraction", 0.0))
        self.pinned_pages.set(getattr(pool, "pinned_pages", 0))
        # two-tier KV sync (kv_tier.py): the pool owns the lifetime
        # tier-traffic integers; fold the deltas into the counters so
        # the cluster's counter-carry and the telemetry scraper's
        # delta decoding see ordinary monotonic counters
        spills = getattr(pool, "spills", None)
        if spills is not None:
            self.kv_spills.inc(spills - self.kv_spills.value)
            self.kv_prefetch_hits.inc(
                pool.prefetch_hits - self.kv_prefetch_hits.value)
            self.kv_prefetch_stalls.inc(
                pool.prefetch_stalls - self.kv_prefetch_stalls.value)
            self.kv_host_pages_used.set(pool.host_pages_used)
            self.kv_resident_fraction.set(pool.resident_fraction)
        else:
            self.kv_host_pages_used.set(0.0)
            self.kv_resident_fraction.set(1.0)
        now = self._now()
        ages = scheduler.queue_ages(now) \
            if hasattr(scheduler, "queue_ages") else []
        self.max_queue_wait_s.set(max(ages) if ages else 0.0)
        self.queue_age_p99_s.set(percentile_of(ages, 99) or 0.0)
        self._rate_samples.append((now, self.tokens_generated.value))
        while len(self._rate_samples) > 2 and \
                now - self._rate_samples[0][0] > self.RATE_WINDOW_S:
            self._rate_samples.popleft()
        t_old, tok_old = self._rate_samples[0]
        self.tokens_per_s.set(
            (self.tokens_generated.value - tok_old) / max(now - t_old, 1e-9))
        if _nv.prof_enabled():
            for g in self.GAUGES:
                v = getattr(self, g).value
                _nv.prof_instant(f"serving.{g}={v:.3f}", 3)

    def snapshot(self) -> dict:
        out = {c: getattr(self, c).value for c in self.COUNTERS}
        now = self._now()
        stale = []
        for g in self.GAUGES:
            gauge = getattr(self, g)
            age = gauge.age_s(now)
            if self.stale_after_s is not None and \
                    (age is None or age > self.stale_after_s):
                # a stale gauge reads as null, never as its last value:
                # "the queue was empty when this replica last reported"
                # must not masquerade as "the queue is empty now"
                out[g] = None
                stale.append(g)
            else:
                out[g] = gauge.value
        out["stale_gauges"] = stale
        for h in self.HISTOGRAMS:
            hist = getattr(self, h)
            out[f"{h}_count"] = hist.count
            for q in (50, 90, 99):
                out[f"{h}_p{q}"] = hist.percentile(q)
        out["uptime_s"] = now - self._t0
        return out


__all__ = ["Counter", "Gauge", "Histogram", "ServingMetrics",
           "percentile_of"]
