"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py).

The reference ships a V100-era per-op timing table
(static_op_benchmark.json) consumed by the auto-parallel planner. Here
the equivalent measured data is this repo's own per-op baseline
(tools/op_bench_baseline.json, recorded by tools/op_bench.py on the
actual backend) — ``static_cost_data``/``get_static_op_time`` read it;
``profile_measure`` points at the measuring tool. The roofline model the
auto-parallel planner actually uses lives in
paddle_tpu/distributed/auto_tuner.py.
"""
from __future__ import annotations

import json
import os

__all__ = ["CostModel"]


def _baseline_path():
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "tools", "op_bench_baseline.json")


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    def static_cost_data(self):
        """Load the measured per-op baseline (backend -> op -> ms)."""
        if self._static_cost_data is None:
            try:
                with open(_baseline_path()) as f:
                    self._static_cost_data = json.load(f)
            except (OSError, ValueError):
                # no repo checkout (installed package) or corrupt file:
                # degrade to empty with a log, never raise from a lookup
                import logging
                logging.getLogger("paddle_tpu").info(
                    "cost_model: no readable baseline at %s",
                    _baseline_path())
                self._static_cost_data = {}
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if not op_name:
            raise ValueError(
                "op_name should not be empty when you want to get static "
                "op time")
        data = self.static_cost_data()
        out = {}
        for backend, entry in data.items():
            ops = entry.get("ops", {}) if isinstance(entry, dict) else {}
            for name, us in ops.items():
                if name == op_name or name.startswith(op_name + "_"):
                    out.setdefault("op_time", us)
                    out.setdefault("unit", entry.get("unit", "us/op"))
                    out.setdefault("backend", backend)
                    out.setdefault("config", name)
        return out

    def profile_measure(self, *args, **kwargs):
        raise NotImplementedError(
            "measure with tools/op_bench.py --record (writes the baseline "
            "this CostModel reads); whole-program cost modeling lives in "
            "paddle_tpu.distributed.auto_tuner")
