"""Top-level framework helpers (reference: python/paddle/framework/ and
python/paddle/base/ misc surface: is_tensor & friends framework.py,
batch.py batch, utils/layers_utils.py:488 check_shape, dlpack
utils/dlpack.py, tensor/to_string.py set_printoptions).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as _random
from ..core.dtype import to_paddle_dtype


# ---- predicates ----

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return bool(to_paddle_dtype(jnp.result_type(x._data)).is_complex)


def is_integer(x):
    return bool(to_paddle_dtype(jnp.result_type(x._data)).is_integer)


def is_floating_point(x):
    return bool(to_paddle_dtype(jnp.result_type(x._data)).is_floating)


def is_empty(x, name=None):
    """0-D bool tensor: does x have zero elements (reference: paddle.is_empty)."""
    return Tensor(jnp.asarray(int(np.prod(x.shape)) == 0 if x.shape else False))


def rank(input, name=None):
    """0-D int32 tensor holding ndim (reference: paddle.rank)."""
    return Tensor(jnp.asarray(input.ndim, jnp.int32))


def shape(input, name=None):
    """1-D int32 tensor holding the shape (reference: paddle.shape)."""
    return Tensor(jnp.asarray(input.shape, jnp.int32))


def tolist(x):
    return x.tolist()


# ---- parameter creation ----

def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone parameter factory (reference: paddle.create_parameter,
    base/layers/tensor.py). Delegates to Layer.create_parameter so the
    init-selection law (Xavier for weights / zeros for bias) and LazyGuard
    deferral live in exactly one place."""
    from ..nn.layer.layers import Layer
    p = Layer().create_parameter(shape, attr=attr, dtype=dtype,
                                 is_bias=is_bias,
                                 default_initializer=default_initializer)
    if p is not None and name is not None:
        p.name = name
    return p


# ---- reader helpers ----

def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (reference: batch.py:26)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be a positive integer")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def check_shape(shape):
    """Validate a shape argument (reference: utils/layers_utils.py:488):
    list/tuple elements must be non-negative ints; a Tensor shape must be
    integer-typed."""
    if isinstance(shape, Tensor):
        if not to_paddle_dtype(jnp.result_type(shape._data)).is_integer:
            raise TypeError("shape tensor must be int32/int64")
        return
    if isinstance(shape, (list, tuple)):
        for e in shape:
            if isinstance(e, Tensor):
                continue
            if not isinstance(e, (int, np.integer)):
                raise TypeError(
                    "All elements in shape must be integers when it's a "
                    "list or tuple")
            if e < 0:
                raise ValueError(
                    "All elements in shape must be non-negative when it's "
                    "a list or tuple")


# ---- dlpack ----

class _DLPackExport:
    """DLPack provider wrapping a jax.Array (modern protocol: consumers
    call ``__dlpack__``/``__dlpack_device__`` themselves; raw capsules are
    single-consume and unsupported by jax>=0.4 import)."""

    __slots__ = ("_arr",)

    def __init__(self, arr):
        self._arr = arr

    def __dlpack__(self, **kwargs):
        return self._arr.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def to_dlpack(x):
    """Export for DLPack consumers (reference: utils/dlpack.py to_dlpack).
    Returns a provider object — ``torch.from_dlpack``, ``np.from_dlpack``,
    and ``jnp.from_dlpack`` all accept it directly."""
    data = x._data if isinstance(x, Tensor) else x
    return _DLPackExport(data)


def from_dlpack(dlpack):
    """Import a DLPack provider (torch/numpy/jax array or to_dlpack
    result) as a Tensor; zero-copy where the producer allows it."""
    if isinstance(dlpack, Tensor):
        return Tensor(dlpack._data)
    return Tensor(jnp.from_dlpack(dlpack))


# ---- RNG state (CUDA-named API mapped to the device RNG) ----

def get_cuda_rng_state():
    """Device RNG state. CUDA-named for reference compatibility
    (python/paddle/framework/random.py get_cuda_rng_state); on this stack
    it is the TPU/global threefry state from core.random."""
    return _random.get_rng_state()


def set_cuda_rng_state(state):
    _random.set_rng_state(state)


def disable_signal_handler():
    """No-op: the reference installs C++ fault handlers it must disable for
    interop (paddle/fluid/platform/init.cc); this runtime installs none."""
    return None


# ---- print options (consumed by Tensor.__repr__) ----

PRINT_OPTIONS = {
    "precision": 6, "threshold": 1000, "edgeitems": 3, "linewidth": 75,
    "sci_mode": None,
}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """(reference: python/paddle/tensor/to_string.py set_printoptions)."""
    if precision is not None:
        PRINT_OPTIONS["precision"] = int(precision)
    if threshold is not None:
        PRINT_OPTIONS["threshold"] = int(threshold)
    if edgeitems is not None:
        PRINT_OPTIONS["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        PRINT_OPTIONS["linewidth"] = int(linewidth)
    if sci_mode is not None:
        PRINT_OPTIONS["sci_mode"] = bool(sci_mode)


__all__ = [
    "is_tensor", "is_complex", "is_integer", "is_floating_point",
    "is_empty", "rank", "shape", "tolist", "create_parameter", "batch",
    "check_shape", "to_dlpack", "from_dlpack", "get_cuda_rng_state",
    "set_cuda_rng_state", "disable_signal_handler", "set_printoptions",
]
