"""Single-process save/load (analog of python/paddle/framework/io.py:773,1020)."""
from __future__ import annotations
import pickle
import numpy as np
from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True, "data": np.asarray(obj.numpy()),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj):
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", t.name)
            return t
        return {k: _from_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **kwargs):
    with open(path, "rb") as f:
        return _from_saveable(pickle.load(f))
