"""paddle_tpu.framework — misc framework-level API (save/load, dtype defaults)."""
from .io import save, load  # noqa: F401
