"""paddle_tpu.device — device management (analog of python/paddle/device/)."""
from ..core.place import set_device, get_device, CPUPlace, TPUPlace, Place, is_compiled_with_tpu  # noqa: F401
import jax as _jax

def device_count():
    return len(_jax.devices())

def synchronize(device=None):
    for d in _jax.live_arrays():
        d.block_until_ready()

def cuda_device_count():  # parity shim
    return 0

def is_compiled_with_cuda():
    return False

def is_compiled_with_xpu():
    return False
