"""paddle_tpu.device — device management (analog of python/paddle/device/).

The reference's Stream/Event classes (python/paddle/device/cuda/streams.py)
wrap CUDA streams; XLA owns stream scheduling on TPU, so Stream/Event here
provide ordering semantics at the dispatch level: ``synchronize`` blocks on
live buffers, Event.record captures the current async frontier.
"""
from __future__ import annotations

import time

import jax as _jax

from ..core.place import (  # noqa: F401
    set_device, get_device, CPUPlace, TPUPlace, Place, is_compiled_with_tpu)


def device_count():
    return len(_jax.devices())


def synchronize(device=None):
    for d in _jax.live_arrays():
        d.block_until_ready()


def cuda_device_count():  # parity shim
    return 0


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def get_all_device_type():
    return sorted({d.platform for d in _jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in _jax.devices()]


class Event:
    """(reference: device/cuda/streams.py Event). record() captures the
    current dispatch frontier; synchronize() drains it; elapsed_time
    between two synced events in ms."""

    def __init__(self, device=None, enable_timing=True):
        self._arrays = []
        self._time = None

    def record(self, stream=None):
        self._arrays = list(_jax.live_arrays())
        self._time = None

    def synchronize(self):
        for a in self._arrays:
            a.block_until_ready()
        if self._time is None:
            self._time = time.perf_counter()

    def query(self):
        return all(a.is_ready() for a in self._arrays)

    def elapsed_time(self, end_event):
        # drain in event order so the start timestamp cannot postdate the
        # end timestamp; if the caller already synced the end event first,
        # ordering is unrecoverable — clamp at zero
        self.synchronize()
        end_event.synchronize()
        return max(0.0, (end_event._time - self._time) * 1e3)


class Stream:
    """XLA enqueues on its own streams; this object provides the reference
    API's ordering handles (wait_event/record_event/synchronize)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _current_stream
        prev, _current_stream = _current_stream, stream
        try:
            yield
        finally:
            _current_stream = prev

    return guard()


class cuda:
    """Namespace shim: paddle.device.cuda.* maps onto the TPU runtime."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        from ..core import native as _nv
        _nv.mem_release_cached()

    @staticmethod
    def max_memory_allocated(device=None):
        from ..core import native as _nv
        return _nv.mem_peak()

    @staticmethod
    def memory_allocated(device=None):
        from ..core import native as _nv
        return _nv.mem_allocated()

    @staticmethod
    def memory_reserved(device=None):
        from ..core import native as _nv
        return _nv.mem_reserved()

    @staticmethod
    def max_memory_reserved(device=None):
        from ..core import native as _nv
        return _nv.mem_peak()

    @staticmethod
    def reset_max_memory_allocated(device=None):
        from ..core import native as _nv
        if hasattr(_nv, "mem_reset_peak"):
            _nv.mem_reset_peak()

    @staticmethod
    def reset_max_memory_reserved(device=None):
        cuda.reset_max_memory_allocated(device)

    @staticmethod
    def current_stream(device=None):
        return current_stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def get_device_properties(device=None):
        import jax
        devs = [d for d in jax.devices()]
        d = devs[0 if device is None else int(
            str(device).rsplit(":", 1)[-1]) if str(device)[-1].isdigit()
            else 0]

        class _Props:
            name = getattr(d, "device_kind", str(d))
            major, minor = 0, 0
            total_memory = (getattr(d, "memory_stats", lambda: {})() or
                            {}).get("bytes_limit", 0)
            multi_processor_count = 1

            def __repr__(self):
                return (f"_gpuDeviceProperties(name='{self.name}', "
                        f"total_memory={self.total_memory})")

        return _Props()

    @staticmethod
    def get_device_name(device=None):
        return cuda.get_device_properties(device).name

    @staticmethod
    def get_device_capability(device=None):
        p = cuda.get_device_properties(device)
        return p.major, p.minor


class xpu:
    """paddle.device.xpu parity shim (vendor-XPU is a sanctioned
    descope; the calls map onto the current accelerator runtime)."""

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        cuda.empty_cache()

    @staticmethod
    def device_count():
        return 0


__all__ = ["set_device", "get_device", "device_count", "synchronize",
           "Stream", "Event", "current_stream", "stream_guard", "cuda",
           "is_compiled_with_tpu", "is_compiled_with_cuda",
           "is_compiled_with_xpu", "xpu", "get_all_device_type",
           "get_available_device"]


def memory_stats(device=None):
    """Per-device memory statistics (reference: paddle/phi/core/memory/
    stats.h DEVICE_MEMORY_STAT_* counters; python device.cuda.memory_*).

    Returns a dict with ``bytes_in_use``/``peak_bytes_in_use``/
    ``bytes_limit`` (whatever the PJRT backend exposes), or None when the
    backend publishes no stats (XLA-CPU, and some pool configurations).
    """
    import jax
    devs = jax.devices()
    idx = 0
    if isinstance(device, int):
        idx = device
    elif isinstance(device, str) and ":" in device:
        idx = int(device.rsplit(":", 1)[1])
    if not 0 <= idx < len(devs):
        raise ValueError(
            f"device index {idx} out of range ({len(devs)} devices)")
    try:
        stats = devs[idx].memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def max_memory_allocated(device=None):
    """Peak bytes in use (reference: device/cuda.max_memory_allocated)."""
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", 0)) if s else 0


def memory_allocated(device=None):
    s = memory_stats(device)
    return int(s.get("bytes_in_use", 0)) if s else 0


# ---- reference parity tail (python/paddle/device/__init__.py __all__) ----

def get_cudnn_version():
    """None: no cuDNN on this stack (reference returns the int version;
    callers use None/int checks for feature gates)."""
    return None


class XPUPlace:
    """Accepted for API parity; resolves to the accelerator place
    (reference: paddle.device.XPUPlace)."""

    def __new__(cls, dev_id=0):
        from ..core.place import TPUPlace
        return TPUPlace(dev_id)


class IPUPlace:
    def __new__(cls, dev_id=0):
        from ..core.place import TPUPlace
        return TPUPlace(dev_id)


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    """XLA fills CINN's role here; the flag answers the reference question
    'is the graph compiler available' — it is."""
    return True


def is_compiled_with_rocm():
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_custom_device(device_type=None):
    """True when a custom device type is registered (reference:
    framework.core.is_compiled_with_custom_device). PJRT plugins are the
    custom-runtime ABI here: register with :func:`register_custom_device`."""
    from ..core.place import _CUSTOM_DEVICE_TYPES
    if device_type is None:
        return bool(_CUSTOM_DEVICE_TYPES)
    return device_type in _CUSTOM_DEVICE_TYPES


def register_custom_device(device_type, jax_platform=None):
    """Register a custom device type backed by a JAX/PJRT platform — the
    pluggable-backend surface (reference: the CustomDevice runtime ABI,
    paddle/phi/backends/custom/custom_device.cc; on this stack a PJRT
    plugin IS the custom runtime, so registration is a name mapping).
    After registration, ``paddle.set_device(f"{device_type}:0")``,
    CustomPlace, and tensor placement all resolve through
    ``jax.devices(jax_platform)``."""
    from ..core.place import register_custom_device as _reg
    _reg(device_type, jax_platform)


def get_all_custom_device_type():
    from ..core.place import _CUSTOM_DEVICE_TYPES
    return sorted(_CUSTOM_DEVICE_TYPES)


def get_available_custom_device():
    from ..core.place import _CUSTOM_DEVICE_TYPES, _custom_devices
    out = []
    for name, plat in sorted(_CUSTOM_DEVICE_TYPES.items()):
        out.extend(f"{name}:{i}"
                   for i in range(len(_custom_devices(plat))))
    return out


def set_stream(stream=None):
    """Streams are implicit in the PJRT runtime; returns the current
    stream object for parity (reference: device/__init__.py set_stream)."""
    return current_stream()
