"""Sparse conv / pooling / attention functionals (reference:
python/paddle/sparse/nn/functional/{conv,pooling,transformer}.py over the
22.5k-LoC CUDA rulebook kernels, paddle/phi/kernels/sparse/).

TPU formulation: the RULEBOOK (which input site feeds which output site
through which kernel offset) is data-dependent, so it is built on the
host from the integer coordinates — the same role the reference's
rulebook kernels play on GPU — while all FLOPs (per-offset gathers,
values @ W_k matmuls, segment reductions) run in jnp and are
differentiable w.r.t. values and weights. Coordinates are static per
call; training pipelines reuse the rulebook across steps when the
point cloud is fixed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import eager_apply


def _triple(v, nd=3):
    return (v,) * nd if isinstance(v, int) else tuple(v)


def _coords_values(x):
    bcoo = x._bcoo
    return (np.asarray(bcoo.indices), bcoo.data,
            tuple(int(s) for s in bcoo.shape))


def _make_coo(indices_np, values_t, shape):
    """Build a SparseCooTensor whose values stay ON the autograd tape:
    ``values_t`` is the tracked Tensor an op produced."""
    import jax.experimental.sparse as jsparse

    from . import SparseCooTensor
    bcoo = jsparse.BCOO((values_t._data, jnp.asarray(indices_np)),
                        shape=shape)
    out = SparseCooTensor(bcoo, stop_gradient=values_t.stop_gradient)
    out._values_t = values_t
    return out


_rulebook_cache: dict = {}


def _build_rulebook(coords, shape, kernel, stride, padding, subm):
    """(out_coords [m, 1+nd], rules, out_shape) — rules[k] =
    (in_rows, out_rows): input site i feeds output site o through kernel
    offset k. Cached on the coordinate bytes: training loops over a fixed
    point cloud build each layer's rulebook once.

    Reference: phi/kernels/sparse/gpu/conv_kernel.cu rulebook
    construction; submanifold keeps out_coords == in_coords."""
    key = (coords.tobytes(), coords.shape, tuple(shape),
           tuple(_triple(kernel, coords.shape[1] - 1)),
           tuple(_triple(stride, coords.shape[1] - 1)),
           tuple(_triple(padding, coords.shape[1] - 1)), subm)
    hit = _rulebook_cache.get(key)
    if hit is not None:
        return hit
    out = _build_rulebook_impl(coords, shape, kernel, stride, padding,
                               subm)
    if len(_rulebook_cache) > 64:   # bounded: drop the oldest entry
        _rulebook_cache.pop(next(iter(_rulebook_cache)))
    _rulebook_cache[key] = out
    return out


def _build_rulebook_impl(coords, shape, kernel, stride, padding, subm):
    nd = coords.shape[1] - 1
    k = _triple(kernel, nd)
    s = _triple(stride, nd)
    p = _triple(padding, nd)
    sp = shape[1:1 + nd]
    in_map = {tuple(c): i for i, c in enumerate(coords)}

    rules = {}
    if subm:
        out_map = in_map
        out_sp = sp
        for i, c in enumerate(coords):
            b = c[0]
            for ki, off in enumerate(np.ndindex(*k)):
                oc = tuple(c[1 + d] + (k[d] // 2) - off[d]
                           for d in range(nd))
                if any(not (0 <= oc[d] < sp[d]) for d in range(nd)):
                    continue
                o = out_map.get((b, *oc))
                if o is not None:
                    rules.setdefault(ki, ([], []))
                    rules[ki][0].append(i)
                    rules[ki][1].append(o)
        out_coords = coords
    else:
        # ONE pass: output coordinates materialize as rules reference them
        out_sp = tuple((sp[d] + 2 * p[d] - k[d]) // s[d] + 1
                       for d in range(nd))
        out_map = {}
        out_list = []
        for i, c in enumerate(coords):
            b = c[0]
            for ki, off in enumerate(np.ndindex(*k)):
                oc = []
                ok = True
                for d in range(nd):
                    num = c[1 + d] + p[d] - off[d]
                    if num % s[d] or not (
                            0 <= num // s[d] < out_sp[d]):
                        ok = False
                        break
                    oc.append(num // s[d])
                if not ok:
                    continue
                key = (b, *oc)
                o = out_map.get(key)
                if o is None:
                    o = out_map[key] = len(out_list)
                    out_list.append(key)
                rules.setdefault(ki, ([], []))
                rules[ki][0].append(i)
                rules[ki][1].append(o)
        out_coords = np.asarray(out_list, coords.dtype).reshape(
            -1, 1 + nd)
    rules = {ki: (np.asarray(a, np.int32), np.asarray(b_, np.int32))
             for ki, (a, b_) in rules.items()}
    full_out_shape = (shape[0],) + out_sp + (shape[-1],)
    return out_coords, rules, full_out_shape


def _sparse_conv(x, weight, bias, stride, padding, subm, op_name):
    """weight: [*kernel, C_in, C_out] (the reference's sparse conv layout).

    out_vals[o] = sum_k vals[rules_k.in] @ W_k  (segment-sum scatter)."""
    coords, _, shape = _coords_values(x)
    wshape = tuple(weight.shape)
    nd = coords.shape[1] - 1
    kshape = wshape[:nd]
    cout = wshape[-1]
    out_coords, rules, out_shape = _build_rulebook(
        coords, shape, kshape, stride, padding, subm)
    m = len(out_coords)
    # pass TENSORS so eager_apply puts values/weight/bias on the tape
    args = [x.values_tensor, weight] + ([bias] if bias is not None else [])

    def fn(vals, w, *maybe_bias):
        w_flat = w.reshape((-1,) + w.shape[nd:])    # [prod(k), Cin, Cout]
        out = jnp.zeros((m, cout), vals.dtype)
        for ki, (rin, rout) in rules.items():
            contrib = vals[jnp.asarray(rin)] @ w_flat[ki]
            out = out + jax.ops.segment_sum(
                contrib, jnp.asarray(rout), num_segments=m)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    out_vals = eager_apply(op_name, fn, tuple(args), {})
    new_shape = out_shape[:-1] + (cout,)
    return _make_coo(out_coords, out_vals, new_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", key=None, name=None):
    """Sparse 3-D convolution (reference: sparse/nn/functional/conv.py:362,
    kernel phi/kernels/sparse/gpu/conv_kernel.cu)."""
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, False,
                        "sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: output sites == input sites
    (conv.py:468 — the backbone op of point-cloud networks)."""
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse subm_conv3d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, True,
                        "sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", key=None, name=None):
    if dilation not in (1, (1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv2d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, False,
                        "sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if dilation not in (1, (1, 1)) or groups != 1:
        raise NotImplementedError("sparse subm_conv2d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, True,
                        "sparse_subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling over active sites (reference:
    sparse/nn/functional/pooling.py:36, pool_kernel.cu)."""
    coords, _, shape = _coords_values(x)
    stride = stride if stride is not None else kernel_size
    out_coords, rules, out_shape = _build_rulebook(
        coords, shape, kernel_size, stride, padding, False)
    m = len(out_coords)
    values = x.values_tensor

    def fn(vals):
        out = jnp.full((m,) + vals.shape[1:], -jnp.inf, vals.dtype)
        for ki, (rin, rout) in rules.items():
            out = jnp.maximum(out, jax.ops.segment_max(
                vals[jnp.asarray(rin)], jnp.asarray(rout),
                num_segments=m))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out_vals = eager_apply("sparse_max_pool3d", fn, (values,), {})
    return _make_coo(out_coords, out_vals, out_shape)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """CSR-masked attention (reference: sparse/nn/functional/
    transformer.py attention + sparse_attention kernel): scores are
    computed ONLY at the mask's stored positions, softmax runs per row
    over stored entries, and the weighted sum hits only stored columns.

    query/key/value: dense [B, H, M, D]; sparse_mask: SparseCsrTensor
    [B*H, M, M] (its crows/cols give the layout; values are ignored).
    Returns dense [B, H, M, D].
    """
    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError(
            "sparse attention: key_padding_mask/attn_mask are not "
            "supported — bake them into the CSR layout")
    crows = np.asarray(sparse_mask.crows().numpy()).reshape(-1)
    cols = np.asarray(sparse_mask.cols().numpy()).reshape(-1)
    q = query._data if hasattr(query, "_data") else jnp.asarray(query)
    b, h, mrows, d = q.shape
    bh = b * h
    # per-(bh) CSR blocks laid out back to back
    n_per = len(crows) // bh
    rows_np, cols_np, heads_np = [], [], []
    pos = 0
    for g in range(bh):
        cr = crows[g * n_per:(g + 1) * n_per]
        for r in range(mrows):
            for _ in range(int(cr[r + 1] - cr[r])):
                rows_np.append(r)
                heads_np.append(g)
        cnt = int(cr[mrows] - cr[0])
        cols_np.extend(cols[pos:pos + cnt])
        pos += cnt
    rows_np = np.asarray(rows_np, np.int32)
    cols_np = np.asarray(cols_np, np.int32)
    heads_np = np.asarray(heads_np, np.int32)
    nnz = len(rows_np)
    seg = heads_np.astype(np.int64) * mrows + rows_np   # global row id

    def fn(q, k, v):
        qf = q.reshape(bh, mrows, d)
        kf = k.reshape(bh, mrows, d)
        vf = v.reshape(bh, mrows, d)
        qi = qf[heads_np, rows_np]                      # [nnz, d]
        kj = kf[heads_np, cols_np]
        s = (qi * kj).sum(-1) / jnp.sqrt(jnp.asarray(d, q.dtype))
        seg_j = jnp.asarray(seg)
        smax = jax.ops.segment_max(s, seg_j, num_segments=bh * mrows)
        e = jnp.exp(s - smax[seg_j])
        z = jax.ops.segment_sum(e, seg_j, num_segments=bh * mrows)
        p = e / z[seg_j]
        out = jax.ops.segment_sum(p[:, None] * vf[heads_np, cols_np],
                                  seg_j, num_segments=bh * mrows)
        return out.reshape(b, h, mrows, d)

    _ = nnz
    return eager_apply("sparse_attention", fn, (query, key, value), {})


__all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d", "max_pool3d",
           "attention"]


# -- value-space activations (reference: sparse/nn/functional/activation.py)

def relu(x, name=None):
    from . import relu as _relu
    return _relu(x)


def relu6(x, name=None):
    from . import relu6 as _relu6
    return _relu6(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from . import leaky_relu as _lrelu
    return _lrelu(x, negative_slope)


def softmax(x, axis=-1, name=None):
    from . import softmax as _softmax
    return _softmax(x, axis)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0,
                      dilation=1, groups=1, data_format="NHWC", key=None,
                      name=None):
    """Implicit-GEMM submanifold conv (reference:
    sparse/nn/functional/conv.py subm_conv2d_igemm — a kernel-choice
    variant of subm_conv2d; on this stack the gather+matmul rulebook
    path IS the implicit GEMM, so both names run the same lowering)."""
    return subm_conv2d(x, weight, bias=bias, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       data_format=data_format)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0,
                      dilation=1, groups=1, data_format="NDHWC", key=None,
                      name=None):
    """See subm_conv2d_igemm."""
    return subm_conv3d(x, weight, bias=bias, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       data_format=data_format)
