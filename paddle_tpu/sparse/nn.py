"""sparse.nn — layers over sparse tensors (analog of python/paddle/sparse/nn/).

The reference's sparse layer zoo (python/paddle/sparse/nn/layer/) wraps the
CUDA rulebook kernels; the TPU-native shape keeps sparse COO/CSR as the
STORAGE format and runs layer math through XLA on the (BCOO-backed) values:
activations apply to ``values`` only (zeros map to zeros), Linear rides the
sparse @ dense matmul, norms densify per feature — the XLA-friendly paths
until Pallas gather kernels land for the conv family (documented dense
fallback, sparse/__init__.py conv notes).
"""
from __future__ import annotations

import numpy as np


class _ValueActivation:
    """Elementwise activation f with f(0)=0: applies to stored values only."""

    _fn_name: str = ""

    def __call__(self, x):
        from . import __dict__ as sparse_ns
        return sparse_ns[self._fn_name](x)


class ReLU(_ValueActivation):
    _fn_name = "relu"


class ReLU6:
    def __call__(self, x):
        from . import relu6
        return relu6(x)


class LeakyReLU:
    def __init__(self, negative_slope=0.01):
        self.negative_slope = negative_slope

    def __call__(self, x):
        from . import leaky_relu
        return leaky_relu(x, self.negative_slope)


class Softmax:
    """Softmax over the last dense axis of a CSR/COO matrix (reference:
    sparse/nn/layer/activation.py Softmax — per-row over stored values)."""

    def __init__(self, axis=-1):
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1")

    def __call__(self, x):
        from . import softmax
        return softmax(x)


class Linear:
    """y = x @ W + b on a sparse x (reference: sparse matmul kernels)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None):
        from .. import nn as dense_nn
        self._inner = dense_nn.Linear(in_features, out_features,
                                      weight_attr=weight_attr,
                                      bias_attr=bias_attr)
        self.weight = self._inner.weight
        self.bias = self._inner.bias

    def parameters(self):
        return self._inner.parameters()

    def __call__(self, x):
        from . import matmul
        out = matmul(x, self.weight)   # dense Tensor, on the tape
        if self.bias is not None:
            out = out + self.bias      # Tensor add keeps the tape intact
        return out


class BatchNorm:
    """Feature batch-norm over the dense trailing dim of a COO tensor
    (reference: sparse/nn/layer/norm.py BatchNorm — stats over stored
    points)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        import jax.numpy as jnp
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.running_mean = jnp.zeros((num_features,))
        self.running_var = jnp.ones((num_features,))
        self.training = True

    def __call__(self, x):
        import jax.numpy as jnp
        from . import SparseCooTensor
        vals = x._bcoo.data if hasattr(x, "_bcoo") else None
        if vals is None:
            raise ValueError("sparse BatchNorm expects a SparseCooTensor")
        if vals.ndim < 2 or vals.shape[-1] != self.num_features:
            raise ValueError(
                "sparse BatchNorm needs a dense trailing feature dim of "
                f"size {self.num_features} (build the tensor with "
                "to_sparse_coo(dense, sparse_dim=ndim-1)); got values shape "
                f"{vals.shape}")
        if self.training:
            mean = vals.mean(axis=0)
            var = vals.var(axis=0)
            self.running_mean = (self.momentum * self.running_mean
                                 + (1 - self.momentum) * mean)
            self.running_var = (self.momentum * self.running_var
                                + (1 - self.momentum) * var)
        else:
            mean, var = self.running_mean, self.running_var
        new_vals = (vals - mean) / jnp.sqrt(var + self.epsilon)
        import jax.experimental.sparse as jsparse
        bcoo = jsparse.BCOO((new_vals, x._bcoo.indices), shape=x._bcoo.shape)
        return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Linear", "BatchNorm"]


class _SparseConvNd:
    """Sparse conv layer base (reference: sparse/nn/layer/conv.py _Conv3D).
    Weight layout [*kernel, C_in, C_out]."""

    _subm = False
    _nd = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        import numpy as np

        from ..core.tensor import Tensor
        from ..core import random as _rng
        import jax

        k = (kernel_size,) * self._nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.kernel_size = k
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / np.sqrt(fan_in)
        wkey = _rng.next_key()
        self.weight = Tensor(jax.random.uniform(
            wkey, k + (in_channels, out_channels),
            minval=-bound, maxval=bound), stop_gradient=False)
        self.bias = None
        if bias_attr is not False:
            self.bias = Tensor(jax.random.uniform(
                _rng.next_key(), (out_channels,),
                minval=-bound, maxval=bound), stop_gradient=False)

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None
                                else [])

    def __call__(self, x):
        from . import functional as F
        fn = {(3, False): F.conv3d, (3, True): F.subm_conv3d,
              (2, False): F.conv2d, (2, True): F.subm_conv2d}[
                  (self._nd, self._subm)]
        return fn(x, self.weight, self.bias, stride=self.stride,
                  padding=self.padding, dilation=self.dilation,
                  groups=self.groups)


class Conv3D(_SparseConvNd):
    _subm, _nd = False, 3


class SubmConv3D(_SparseConvNd):
    _subm, _nd = True, 3


class Conv2D(_SparseConvNd):
    _subm, _nd = False, 2


class SubmConv2D(_SparseConvNd):
    _subm, _nd = True, 2


class MaxPool3D:
    """Sparse max pooling layer (reference: sparse/nn/layer/pooling.py)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        from . import functional as F
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


__all__ += ["Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D"]


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BatchNorm (reference:
    sparse/nn/layer/norm.py SyncBatchNorm). Under GSPMD the batch
    statistics of a sharded values tensor are computed globally by the
    compiler-inserted collectives — the dedicated NCCL sync path of the
    reference collapses into BatchNorm on this stack."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert sparse BatchNorm layers (reference API)."""
        if isinstance(layer, BatchNorm) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm.__new__(SyncBatchNorm)
            out.__dict__.update(layer.__dict__)
            return out
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


from . import functional  # noqa: E402,F401  (sparse.nn.functional)

__all__ += ["SyncBatchNorm", "functional"]
