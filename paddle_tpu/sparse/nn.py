"""sparse.nn — layers over sparse tensors (analog of python/paddle/sparse/nn/).

Minimal surface: ReLU layer + SubmConv stub-free Conv3D via dense fallback
(the reference's submanifold sparse conv is a CUDA-only rulebook kernel;
on TPU the dense conv over the densified block is the XLA-friendly path
until a Pallas gather-conv lands).
"""
from __future__ import annotations


class ReLU:
    def __call__(self, x):
        from . import relu as _relu
        return _relu(x)


__all__ = ["ReLU"]
