"""paddle_tpu.sparse — COO/CSR sparse tensors and ops
(analog of python/paddle/sparse/, kernels paddle/phi/kernels/sparse/).

TPU-native design: sparse tensors wrap ``jax.experimental.sparse`` BCOO
(batched-COO, the XLA-lowering-friendly format). The reference's CUDA
sparse kernels (spmm via cuSPARSE etc.) map to BCOO dot_general lowerings
that XLA tiles onto the MXU. CSR is kept as a thin view with
crows/cols/values accessors for API parity; compute routes through BCOO.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..core.dispatch import eager_apply
from . import nn  # noqa: F401  (after Tensor import to avoid cycles)


def _apply(name, fn, *args):
    return eager_apply(name, fn, args, {})


class SparseCooTensor(Tensor):
    """Eager COO tensor: wraps a BCOO; densifies LAZILY on first dense use.

    (reference: paddle/phi/core/sparse_coo_tensor.h). Shape/dtype queries
    read BCOO metadata; ``_data`` (and thus any dense op) materializes the
    dense array once and caches it.
    """

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        # initialize Tensor metadata WITHOUT materializing the dense array
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._output_slot = 0
        self.name = f"sparse_coo_{id(self)}"
        self.persistable = False
        self._grad_hooks = []

    # lazy dense buffer: the subclass property shadows the Tensor slot
    @property
    def _data(self):
        d = self.__dict__.get("_dense")
        if d is None:
            d = self._bcoo.todense()
            self.__dict__["_dense"] = d
        return d

    @_data.setter
    def _data(self, v):
        self.__dict__["_dense"] = v

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def ndim(self):
        return len(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import to_paddle_dtype
        return to_paddle_dtype(self._bcoo.data.dtype)

    @property
    def indices_tensor(self):
        return Tensor(self._bcoo.indices.T)

    @property
    def values_tensor(self):
        # ONE stable Tensor identity per sparse tensor: ops attach their
        # tape-tracked output values here, and for leaves the same object
        # must be returned every time so gradients ACCUMULATE on it
        vt = getattr(self, "_values_t", None)
        if vt is None:
            vt = Tensor(self._bcoo.data, stop_gradient=self.stop_gradient)
            self._values_t = vt
        return vt

    def indices(self):
        return self.indices_tensor

    def values(self):
        return self.values_tensor

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


class SparseCsrTensor(SparseCooTensor):
    """CSR view over BCOO (reference: paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, bcoo, crows, cols, stop_gradient=True):
        super().__init__(bcoo, stop_gradient=stop_gradient)
        self._crows = crows
        self._cols = cols

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build COO from [ndim, nnz] indices + [nnz] values
    (reference: python/paddle/sparse/creation.py sparse_coo_tensor)."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
    val = np.asarray(values.numpy() if isinstance(values, Tensor) else values)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        val = val.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((jnp.asarray(val), jnp.asarray(idx.T)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build CSR (2D) (reference: sparse/creation.py sparse_csr_tensor)."""
    crows_np = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    vals_np = np.asarray(values.numpy() if isinstance(values, Tensor) else values)
    shape = tuple(shape)
    if len(shape) == 3:
        # batched CSR [B, M, N] (reference layout: crows holds B blocks of
        # length M+1, cols/values concatenated per block)
        nb, m = shape[0], shape[1]
        per = m + 1
        rows_l, batch_l = [], []
        for g in range(nb):
            cr = crows_np[g * per:(g + 1) * per]
            counts = np.diff(cr)
            rows_l.append(np.repeat(np.arange(m), counts))
            batch_l.append(np.full(int(counts.sum()), g))
        idx = np.stack([np.concatenate(batch_l),
                        np.concatenate(rows_l), cols_np])
    else:
        rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
        idx = np.stack([rows, cols_np])
    bcoo = jsparse.BCOO((jnp.asarray(vals_np), jnp.asarray(idx.T)),
                        shape=shape)
    return SparseCsrTensor(bcoo, jnp.asarray(crows_np), jnp.asarray(cols_np),
                           stop_gradient=stop_gradient)


def to_sparse_coo(dense, sparse_dim=None):
    """sparse_dim < ndim keeps the trailing dims dense (hybrid COO — the
    point-cloud [N, C] layout the reference's sparse conv/norm layers use)."""
    x = dense._data if isinstance(dense, Tensor) else jnp.asarray(dense)
    n_sparse = sparse_dim if sparse_dim is not None else x.ndim
    bcoo = jsparse.BCOO.fromdense(x, n_dense=x.ndim - n_sparse)
    return SparseCooTensor(bcoo, stop_gradient=getattr(dense, "stop_gradient", True))


def matmul(a, b):
    """Sparse @ dense -> dense (reference: sparse/binary.py matmul; the
    cuSPARSE spmm path). BCOO dot_general gives XLA a gather+MXU plan."""
    if isinstance(a, SparseCooTensor) and isinstance(b, Tensor) \
            and not isinstance(b, SparseCooTensor):
        bcoo = a._bcoo
        return _apply("sparse_matmul",
                      lambda bv, dense: jsparse.BCOO(
                          (bv, bcoo.indices), shape=bcoo.shape) @ dense,
                      a.values_tensor, b)
    from ..tensor.linalg import matmul as dense_matmul
    a_d = a.to_dense() if isinstance(a, SparseCooTensor) else a
    b_d = b.to_dense() if isinstance(b, SparseCooTensor) else b
    return dense_matmul(a_d, b_d)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        out = a._bcoo + b._bcoo
        return SparseCooTensor(out.sum_duplicates(nse=out.nse))
    return a.to_dense() + b.to_dense()


def _unary(name, fn):
    def op(x):
        if not isinstance(x, SparseCooTensor):
            raise TypeError(f"sparse.{name} expects a sparse tensor")
        # route through the eager op layer so the TAPE survives chains of
        # sparse ops (conv -> relu -> conv trains every layer)
        vals_t = _apply(f"sparse_{name}", fn, x.values_tensor)
        new = jsparse.BCOO((vals_t._data, x._bcoo.indices),
                           shape=x._bcoo.shape)
        out = SparseCooTensor(new, stop_gradient=vals_t.stop_gradient)
        out._values_t = vals_t
        return out
    op.__name__ = name
    return op


# value-wise ops preserve the sparsity pattern (reference: sparse/unary.py)
relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)

relu6 = _unary("relu6", lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    return _unary("leaky_relu",
                  lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis=-1):
    """Row-wise softmax over STORED values of a 2-D sparse matrix
    (reference: sparse/nn functional softmax — absent entries are excluded,
    not treated as zeros). Routed through the eager op layer so gradients
    chain through sparse pipelines (SDDMM -> softmax -> spmm)."""
    if axis != -1:
        raise ValueError("sparse softmax supports axis=-1")
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.softmax expects a sparse tensor")
    bcoo = x._bcoo
    if bcoo.indices.shape[-1] != 2 or bcoo.data.ndim != 1:
        raise ValueError("sparse softmax supports 2-D COO matrices")
    n = bcoo.shape[0]

    def fn(v, rows):
        m = jax.ops.segment_max(v, rows, num_segments=n)
        e = jnp.exp(v - m[rows])
        s = jax.ops.segment_sum(e, rows, num_segments=n)
        return e / s[rows]

    vals_t = _apply("sparse_softmax", fn, x.values_tensor,
                    Tensor(bcoo.indices[:, 0].astype(jnp.int32)))
    new = jsparse.BCOO((vals_t._data, bcoo.indices), shape=bcoo.shape)
    out = SparseCooTensor(new, stop_gradient=vals_t.stop_gradient)
    out._values_t = vals_t
    return out


pow = None  # needs a scalar arg


def sparse_pow(x, factor):
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


pow = sparse_pow

# ---- unary tail (reference: python/paddle/sparse/unary.py) — value-wise,
# pattern-preserving; grads flow through the values tape like relu above
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)
acos = _unary("acos", jnp.arccos)
acosh = _unary("acosh", jnp.arccosh)


def full_like(x, fill_value, dtype=None):
    """Sparse tensor with x's pattern, every stored value = fill_value
    (reference: sparse_ops.yaml full_like)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.full_like expects a sparse tensor")
    from ..core.dtype import to_jax_dtype
    dt = to_jax_dtype(dtype) if dtype is not None else x._bcoo.data.dtype
    vals = jnp.full(x._bcoo.data.shape, fill_value, dt)
    return SparseCooTensor(jsparse.BCOO((vals, x._bcoo.indices),
                                        shape=x._bcoo.shape))


def cast(x, index_dtype=None, value_dtype=None):
    """Cast indices and/or values (reference: sparse/unary.py cast)."""
    from ..core.dtype import to_jax_dtype
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.cast expects a sparse tensor")
    idx = x._bcoo.indices
    if index_dtype is not None:
        idx = idx.astype(to_jax_dtype(index_dtype))
    vals = x._bcoo.data
    if value_dtype is not None:
        vals = vals.astype(to_jax_dtype(value_dtype))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=x._bcoo.shape),
                           stop_gradient=x.stop_gradient)


def coalesce(x):
    """Merge duplicate coordinates, summing values; sorts indices
    (reference: sparse/unary.py coalesce, phi sparse coalesce_kernel)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.coalesce expects a sparse tensor")
    out = x._bcoo.sum_duplicates()
    t = SparseCooTensor(out, stop_gradient=x.stop_gradient)
    t._coalesced = True
    return t


def is_coalesced(x) -> bool:
    """True when indices are unique and row-major sorted (reference:
    Tensor.is_coalesced)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.is_coalesced expects a sparse tensor")
    if getattr(x, "_coalesced", False):
        return True
    idx = np.asarray(x._bcoo.indices)
    if idx.shape[0] <= 1:
        return True
    # lexicographic flat keys must be strictly increasing
    keys = np.zeros(idx.shape[0], np.int64)
    for d in range(idx.shape[1]):
        keys = keys * x._bcoo.shape[d] + idx[:, d]
    return bool(np.all(np.diff(keys) > 0))


def _require_full_sparse(x, op):
    """Pattern ops need indices covering EVERY dim; hybrid COO
    (to_sparse_coo(sparse_dim < ndim)) stores trailing dims densely."""
    if x._bcoo.indices.shape[-1] != len(x._bcoo.shape):
        raise ValueError(
            f"sparse.{op} supports fully-sparse COO only; this tensor "
            f"keeps {len(x._bcoo.shape) - x._bcoo.indices.shape[-1]} "
            "trailing dim(s) dense (hybrid layout) — densify or build "
            "with sparse_dim=ndim")


def reshape(x, shape):
    """Reshape by re-deriving coordinates from flat offsets (reference:
    sparse/unary.py reshape — pattern changes, values ride along)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.reshape expects a sparse tensor")
    _require_full_sparse(x, "reshape")
    old_shape = x._bcoo.shape
    n_elem = int(np.prod(old_shape))
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError(f"reshape shape {shape} has more than one -1")
    known = int(np.prod([s for s in shape if s != -1])) or 1
    if neg:
        if known == 0 or n_elem % known:
            raise ValueError(
                f"cannot infer -1 in {shape} for {n_elem} elements")
    elif known != n_elem:
        raise ValueError(
            f"reshape shape {shape} has {known} elements, tensor has "
            f"{n_elem}")
    new_shape = [n_elem // known if s == -1 else int(s) for s in shape]
    idx = x._bcoo.indices
    flat = jnp.zeros(idx.shape[0], jnp.int32)  # x64 disabled on this stack
    for d in range(idx.shape[1]):
        flat = flat * old_shape[d] + idx[:, d].astype(jnp.int32)
    new_idx = []
    rem = flat
    for s in reversed(new_shape):
        new_idx.append(rem % s)
        rem = rem // s
    new_idx = jnp.stack(list(reversed(new_idx)), axis=1).astype(
        idx.dtype)
    return SparseCooTensor(
        jsparse.BCOO((x._bcoo.data, new_idx), shape=tuple(new_shape)),
        stop_gradient=x.stop_gradient)


def transpose(x, perm):
    """Permute dimensions (reference: sparse/unary.py transpose)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.transpose expects a sparse tensor")
    _require_full_sparse(x, "transpose")
    idx = x._bcoo.indices[:, list(perm)]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx), shape=shape),
                           stop_gradient=x.stop_gradient)


def slice(x, axes, starts, ends):
    """Slice along axes (reference: sparse/unary.py slice): keeps entries
    inside the window, shifts their coordinates."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.slice expects a sparse tensor")
    _require_full_sparse(x, "slice")
    idx = np.asarray(x._bcoo.indices)
    vals = x._bcoo.data
    shape = list(x._bcoo.shape)
    keep = np.ones(idx.shape[0], bool)
    for ax, s, e in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        s = int(s) if s >= 0 else int(s) + shape[ax]
        e = int(e) if e >= 0 else int(e) + shape[ax]
        e = min(e, shape[ax])
        keep &= (idx[:, ax] >= s) & (idx[:, ax] < e)
        shape[ax] = e - s
    sel = np.nonzero(keep)[0]
    new_idx = idx[sel].copy()
    for ax, s, e in zip(axes, starts, ends):
        ax = int(ax) % len(x._bcoo.shape)
        s = int(s) if s >= 0 else int(s) + x._bcoo.shape[ax]
        new_idx[:, ax] -= s
    return SparseCooTensor(
        jsparse.BCOO((vals[jnp.asarray(sel)], jnp.asarray(new_idx)),
                     shape=tuple(shape)),
        stop_gradient=x.stop_gradient)


def sum(x, axis=None, dtype=None, keepdim=False):
    """Sum over stored values (reference: sparse/unary.py sum). Reducing
    every axis gives a dense scalar; a single-axis reduce returns the
    dense result (matches reference semantics of returning sparse only
    when sparsity survives — here the dense XLA reduce wins, documented
    in OPS_INVENTORY)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.sum expects a sparse tensor")
    vt = x.values_tensor
    from ..tensor.math import sum as dense_sum
    if axis is None:
        return dense_sum(vt, dtype=dtype)
    from ..core.dispatch import op_call, OPS

    from ..core.dtype import to_jax_dtype

    def body(vals, idx, *, axis, shape, keepdim, dtype):
        ax = axis % len(shape)
        if dtype is not None:
            vals = vals.astype(dtype)   # accumulate in the requested dtype
        # scatter-add into dense, then reduce the axis: one XLA scatter +
        # reduce beats a segment-sort at these nnz scales (measured note
        # in OPS_INVENTORY)
        dense = jnp.zeros(tuple(shape), vals.dtype).at[
            tuple(idx[:, d] for d in range(len(shape)))].add(vals)
        return dense.sum(axis=ax, keepdims=keepdim)

    OPS.setdefault("sparse_sum", body)
    out = op_call("sparse_sum", body, vt, Tensor(x._bcoo.indices),
                  axis=int(axis), shape=tuple(x._bcoo.shape),
                  keepdim=bool(keepdim),
                  dtype=to_jax_dtype(dtype) if dtype is not None else None)
    return out


def pca_lowrank(x, q=None, center=True, niter=2):
    """Low-rank PCA of a sparse matrix (reference: sparse/unary.py
    pca_lowrank). Computed via the dense SVD path: at the sizes the
    reference supports (q <= min(m, n)) the dense XLA SVD on TPU
    outperforms an iterative sparse method that would serialize matvecs;
    the sparse tensor densifies once here (documented trade-off)."""
    from ..tensor.linalg import pca_lowrank as dense_pca
    return dense_pca(x.to_dense(), q=q, center=center, niter=niter)


# ---- binary family (reference: python/paddle/sparse/binary.py) ----

def _binary_samepattern(name, fn, a, b):
    """Value-wise binary op over a SHARED coordinate pattern. Mismatched
    patterns are handled per op by the callers below (subtract stays
    sparse via add(a, -b); multiply intersects; divide requires the same
    pattern because absent coordinates would densify into 0/0)."""
    if not (isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor)):
        raise TypeError(f"sparse.{name} expects two sparse tensors")
    ia, ib = np.asarray(a._bcoo.indices), np.asarray(b._bcoo.indices)
    if not (ia.shape == ib.shape and np.array_equal(ia, ib)):
        return None
    va = a.values_tensor
    vb = b.values_tensor
    out_v = _apply(f"sparse_{name}", fn, va, vb)
    new = jsparse.BCOO((out_v._data, a._bcoo.indices),
                       shape=a._bcoo.shape)
    out = SparseCooTensor(new, stop_gradient=out_v.stop_gradient)
    out._values_t = out_v
    return out


def subtract(a, b):
    out = _binary_samepattern("subtract", lambda x, y: x - y, a, b)
    if out is not None:
        return out
    return add(a, neg(b))   # mismatched patterns: stays sparse


def multiply(a, b):
    out = _binary_samepattern("multiply", lambda x, y: x * y, a, b)
    if out is not None:
        return out
    # mismatched patterns: the product lives on the INTERSECTION (absent
    # entries are zeros); realize via coalesced pattern merge
    am = coalesce(a)
    bm = coalesce(b)
    ia = np.asarray(am._bcoo.indices)
    ib = np.asarray(bm._bcoo.indices)
    keys_a = {tuple(r): i for i, r in enumerate(ia)}
    sel_a, sel_b = [], []
    for j, r in enumerate(map(tuple, ib)):
        i = keys_a.get(r)
        if i is not None:
            sel_a.append(i)
            sel_b.append(j)
    vals = am._bcoo.data[jnp.asarray(sel_a, dtype=jnp.int32)] * \
        bm._bcoo.data[jnp.asarray(sel_b, dtype=jnp.int32)]
    idx = jnp.asarray(ia[sel_a] if sel_a else
                      np.zeros((0, ia.shape[1]), ia.dtype))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=a._bcoo.shape))


def divide(a, b):
    out = _binary_samepattern("divide", lambda x, y: x / y, a, b)
    if out is not None:
        return out
    raise ValueError(
        "sparse.divide requires both operands to share a coordinate "
        "pattern (absent entries would divide by zero); coalesce() or "
        "mask_as() one operand onto the other's pattern first")


def is_same_shape(a, b) -> bool:
    """Reference: sparse/binary.py is_same_shape."""
    return list(a.shape) == list(b.shape)


def mv(a, vec):
    """Sparse matrix @ dense vector (reference: sparse/binary.py mv)."""
    if not isinstance(a, SparseCooTensor):
        raise TypeError("sparse.mv expects a sparse matrix")
    return matmul(a, vec)


def mask_as(x, mask):
    """Take dense ``x``'s values at ``mask``'s sparsity pattern
    (reference: sparse/binary.py mask_as, sparse_mask kernels)."""
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("mask_as expects a sparse mask")
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    idx = mask._bcoo.indices

    def fn(dense, idxs):
        return dense[tuple(idxs[:, d] for d in range(idxs.shape[1]))]

    vals_t = _apply("sparse_mask_as", fn, xt, Tensor(idx))
    new = jsparse.BCOO((vals_t._data, idx), shape=mask._bcoo.shape)
    out = SparseCooTensor(new, stop_gradient=vals_t.stop_gradient)
    out._values_t = vals_t
    return out


def masked_matmul(x, y, mask):
    """(x @ y) evaluated ONLY at mask's sparsity pattern (reference:
    sparse/binary.py masked_matmul, the SDDMM kernel): computes one dot
    per stored coordinate — O(nnz * k), never materializing the dense
    product."""
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("masked_matmul expects a sparse mask")
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
    idx = mask._bcoo.indices

    def fn(xa, ya, idxs):
        rows = idxs[:, 0]
        cols = idxs[:, 1]
        return jnp.einsum("nk,nk->n", xa[rows, :],
                          ya[:, cols].T)

    vals_t = _apply("sparse_masked_matmul", fn, xt, yt, Tensor(idx))
    new = jsparse.BCOO((vals_t._data, idx), shape=mask._bcoo.shape)
    out = SparseCooTensor(new, stop_gradient=vals_t.stop_gradient)
    out._values_t = vals_t
    return out


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta*input + alpha*(x @ y) with sparse x (reference:
    sparse/multiary.py addmm)."""
    prod = matmul(x, y)
    pt = prod if isinstance(prod, Tensor) else Tensor(jnp.asarray(prod))
    it = input.to_dense() if isinstance(input, SparseCooTensor) else input

    def fn(i, p):
        return beta * i + alpha * p

    return _apply("sparse_addmm", fn, it, pt)


__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "to_sparse_coo", "matmul", "add", "relu",
           "abs", "sin", "tanh", "sqrt", "square", "neg", "pow", "nn",
           "asin", "asinh", "atan", "atanh", "sinh", "tan", "expm1",
           "log1p", "deg2rad", "rad2deg", "isnan", "cast", "coalesce",
           "is_coalesced", "reshape", "transpose", "slice", "sum",
           "pca_lowrank", "subtract", "multiply", "divide",
           "is_same_shape", "mv", "mask_as", "masked_matmul", "addmm",
           "acos", "acosh", "full_like"]

from . import functional  # noqa: E402,F401 — sparse conv/pool/attention
from . import nn as _nn_mod  # noqa: E402
_nn_mod.functional = functional
