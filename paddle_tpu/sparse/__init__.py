"""paddle_tpu.sparse — COO/CSR sparse tensors and ops
(analog of python/paddle/sparse/, kernels paddle/phi/kernels/sparse/).

TPU-native design: sparse tensors wrap ``jax.experimental.sparse`` BCOO
(batched-COO, the XLA-lowering-friendly format). The reference's CUDA
sparse kernels (spmm via cuSPARSE etc.) map to BCOO dot_general lowerings
that XLA tiles onto the MXU. CSR is kept as a thin view with
crows/cols/values accessors for API parity; compute routes through BCOO.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..core.dispatch import eager_apply
from . import nn  # noqa: F401  (after Tensor import to avoid cycles)


def _apply(name, fn, *args):
    return eager_apply(name, fn, args, {})


class SparseCooTensor(Tensor):
    """Eager COO tensor: wraps a BCOO; densifies LAZILY on first dense use.

    (reference: paddle/phi/core/sparse_coo_tensor.h). Shape/dtype queries
    read BCOO metadata; ``_data`` (and thus any dense op) materializes the
    dense array once and caches it.
    """

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        # initialize Tensor metadata WITHOUT materializing the dense array
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._output_slot = 0
        self.name = f"sparse_coo_{id(self)}"
        self.persistable = False
        self._grad_hooks = []

    # lazy dense buffer: the subclass property shadows the Tensor slot
    @property
    def _data(self):
        d = self.__dict__.get("_dense")
        if d is None:
            d = self._bcoo.todense()
            self.__dict__["_dense"] = d
        return d

    @_data.setter
    def _data(self, v):
        self.__dict__["_dense"] = v

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def ndim(self):
        return len(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import to_paddle_dtype
        return to_paddle_dtype(self._bcoo.data.dtype)

    @property
    def indices_tensor(self):
        return Tensor(self._bcoo.indices.T)

    @property
    def values_tensor(self):
        # ONE stable Tensor identity per sparse tensor: ops attach their
        # tape-tracked output values here, and for leaves the same object
        # must be returned every time so gradients ACCUMULATE on it
        vt = getattr(self, "_values_t", None)
        if vt is None:
            vt = Tensor(self._bcoo.data, stop_gradient=self.stop_gradient)
            self._values_t = vt
        return vt

    def indices(self):
        return self.indices_tensor

    def values(self):
        return self.values_tensor

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


class SparseCsrTensor(SparseCooTensor):
    """CSR view over BCOO (reference: paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, bcoo, crows, cols, stop_gradient=True):
        super().__init__(bcoo, stop_gradient=stop_gradient)
        self._crows = crows
        self._cols = cols

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build COO from [ndim, nnz] indices + [nnz] values
    (reference: python/paddle/sparse/creation.py sparse_coo_tensor)."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
    val = np.asarray(values.numpy() if isinstance(values, Tensor) else values)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        val = val.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((jnp.asarray(val), jnp.asarray(idx.T)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build CSR (2D) (reference: sparse/creation.py sparse_csr_tensor)."""
    crows_np = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    vals_np = np.asarray(values.numpy() if isinstance(values, Tensor) else values)
    shape = tuple(shape)
    if len(shape) == 3:
        # batched CSR [B, M, N] (reference layout: crows holds B blocks of
        # length M+1, cols/values concatenated per block)
        nb, m = shape[0], shape[1]
        per = m + 1
        rows_l, batch_l = [], []
        for g in range(nb):
            cr = crows_np[g * per:(g + 1) * per]
            counts = np.diff(cr)
            rows_l.append(np.repeat(np.arange(m), counts))
            batch_l.append(np.full(int(counts.sum()), g))
        idx = np.stack([np.concatenate(batch_l),
                        np.concatenate(rows_l), cols_np])
    else:
        rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
        idx = np.stack([rows, cols_np])
    bcoo = jsparse.BCOO((jnp.asarray(vals_np), jnp.asarray(idx.T)),
                        shape=shape)
    return SparseCsrTensor(bcoo, jnp.asarray(crows_np), jnp.asarray(cols_np),
                           stop_gradient=stop_gradient)


def to_sparse_coo(dense, sparse_dim=None):
    """sparse_dim < ndim keeps the trailing dims dense (hybrid COO — the
    point-cloud [N, C] layout the reference's sparse conv/norm layers use)."""
    x = dense._data if isinstance(dense, Tensor) else jnp.asarray(dense)
    n_sparse = sparse_dim if sparse_dim is not None else x.ndim
    bcoo = jsparse.BCOO.fromdense(x, n_dense=x.ndim - n_sparse)
    return SparseCooTensor(bcoo, stop_gradient=getattr(dense, "stop_gradient", True))


def matmul(a, b):
    """Sparse @ dense -> dense (reference: sparse/binary.py matmul; the
    cuSPARSE spmm path). BCOO dot_general gives XLA a gather+MXU plan."""
    if isinstance(a, SparseCooTensor) and isinstance(b, Tensor) \
            and not isinstance(b, SparseCooTensor):
        bcoo = a._bcoo
        return _apply("sparse_matmul",
                      lambda bv, dense: jsparse.BCOO(
                          (bv, bcoo.indices), shape=bcoo.shape) @ dense,
                      a.values_tensor, b)
    from ..tensor.linalg import matmul as dense_matmul
    a_d = a.to_dense() if isinstance(a, SparseCooTensor) else a
    b_d = b.to_dense() if isinstance(b, SparseCooTensor) else b
    return dense_matmul(a_d, b_d)


def add(a, b):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        out = a._bcoo + b._bcoo
        return SparseCooTensor(out.sum_duplicates(nse=out.nse))
    return a.to_dense() + b.to_dense()


def _unary(name, fn):
    def op(x):
        if not isinstance(x, SparseCooTensor):
            raise TypeError(f"sparse.{name} expects a sparse tensor")
        # route through the eager op layer so the TAPE survives chains of
        # sparse ops (conv -> relu -> conv trains every layer)
        vals_t = _apply(f"sparse_{name}", fn, x.values_tensor)
        new = jsparse.BCOO((vals_t._data, x._bcoo.indices),
                           shape=x._bcoo.shape)
        out = SparseCooTensor(new, stop_gradient=vals_t.stop_gradient)
        out._values_t = vals_t
        return out
    op.__name__ = name
    return op


# value-wise ops preserve the sparsity pattern (reference: sparse/unary.py)
relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
neg = _unary("neg", jnp.negative)

relu6 = _unary("relu6", lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    return _unary("leaky_relu",
                  lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis=-1):
    """Row-wise softmax over STORED values of a 2-D sparse matrix
    (reference: sparse/nn functional softmax — absent entries are excluded,
    not treated as zeros)."""
    if axis != -1:
        raise ValueError("sparse softmax supports axis=-1")
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.softmax expects a sparse tensor")
    bcoo = x._bcoo
    if bcoo.indices.shape[-1] != 2 or bcoo.data.ndim != 1:
        raise ValueError("sparse softmax supports 2-D COO matrices")
    n = bcoo.shape[0]
    rows = bcoo.indices[:, 0]
    v = bcoo.data
    m = jax.ops.segment_max(v, rows, num_segments=n)
    e = jnp.exp(v - m[rows])
    s = jax.ops.segment_sum(e, rows, num_segments=n)
    new = jsparse.BCOO((e / s[rows], bcoo.indices), shape=bcoo.shape)
    return SparseCooTensor(new, stop_gradient=x.stop_gradient)


pow = None  # needs a scalar arg


def sparse_pow(x, factor):
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


pow = sparse_pow

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "to_sparse_coo", "matmul", "add", "relu",
           "abs", "sin", "tanh", "sqrt", "square", "neg", "pow", "nn"]

from . import functional  # noqa: E402,F401 — sparse conv/pool/attention
from . import nn as _nn_mod  # noqa: E402
_nn_mod.functional = functional
