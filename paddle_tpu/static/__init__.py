"""paddle_tpu.static — static-graph compatibility surface.

The reference's Program/Executor world (python/paddle/static/,
base/executor.py:812) collapses on this stack: "static graph" IS the jit
path (trace once, compile once, run many). This module keeps the names
users reach for — InputSpec, save/load_inference_model — mapped onto the
jit artifact format.
"""
from ..jit.save_load import InputSpec  # noqa: F401
from ..jit import save_load as _sl


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: python/paddle/static/io.py save_inference_model.

    On this stack the "inference program" is the jit artifact: pass the
    model (a Layer or callable) as ``fetch_vars`` and its input specs as
    ``feed_vars`` — the call produces the same StableHLO artifact as
    ``paddle_tpu.jit.save``. Program/Variable graphs do not exist here,
    so passing raw fetch tensors raises with that guidance.
    """
    from .. import jit as _jit
    from ..nn import Layer

    target = fetch_vars
    if isinstance(target, (list, tuple)) and len(target) == 1:
        target = target[0]
    if isinstance(target, Layer) or (callable(target)
                                     and not isinstance(target, type)):
        specs = list(feed_vars) if isinstance(feed_vars, (list, tuple))             else [feed_vars]
        return _jit.save(target, path_prefix, input_spec=specs)
    raise NotImplementedError(
        "program-based save is not part of the TPU stack; pass the model "
        "as fetch_vars (save_inference_model(path, [InputSpec(...)], "
        "model)) or use paddle_tpu.jit.save — same artifact")


def load_inference_model(path_prefix, executor=None, **kwargs):
    layer = _sl.load(path_prefix)
    return layer


# ``paddle.static.nn`` namespace: control-flow ops + the legacy layer
# builders (reference: python/paddle/static/nn/)
from . import nn  # noqa: E402,F401

# Program / Executor world (reference: python/paddle/static/__init__.py)
from .program import (  # noqa: E402,F401
    Program, Executor, Variable, program_guard, data,
    default_main_program, default_startup_program, global_scope,
    scope_guard, Scope, cpu_places, save, load,
    append_backward, gradients, py_func, name_scope, Print,
)
from .extras import (  # noqa: E402,F401
    BuildStrategy, CompiledProgram, ExponentialMovingAverage,
    WeightNormParamAttr, IpuStrategy, IpuCompiledProgram, ipu_shard_guard,
    create_global_var, device_guard, accuracy, auc, cuda_places,
    xpu_places, set_ipu_shard, ctr_metric_bundle,
)
from .serialization import (  # noqa: E402,F401
    serialize_program, serialize_persistables, deserialize_program,
    deserialize_persistables, save_to_file, load_from_file,
    normalize_program, load_program_state, set_program_state,
)
from ..nn.layer.layers import ParamAttr  # noqa: E402,F401
from ..framework.infra import create_parameter  # noqa: E402,F401

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "nn", "Program", "Executor", "Variable", "program_guard",
           "data", "default_main_program", "default_startup_program",
           "global_scope", "scope_guard", "Scope", "cpu_places", "save",
           "load", "append_backward", "gradients", "py_func", "name_scope",
           "Print", "BuildStrategy", "CompiledProgram",
           "ExponentialMovingAverage", "WeightNormParamAttr", "ParamAttr",
           "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard",
           "create_global_var", "device_guard", "accuracy", "auc",
           "cuda_places", "xpu_places", "set_ipu_shard",
           "ctr_metric_bundle", "create_parameter", "serialize_program",
           "serialize_persistables", "deserialize_program",
           "deserialize_persistables", "save_to_file", "load_from_file",
           "normalize_program", "load_program_state", "set_program_state"]
