"""paddle_tpu.static — static-graph compatibility surface.

The reference's Program/Executor world (python/paddle/static/,
base/executor.py:812) collapses on this stack: "static graph" IS the jit
path (trace once, compile once, run many). This module keeps the names
users reach for — InputSpec, save/load_inference_model — mapped onto the
jit artifact format.
"""
from ..jit.save_load import InputSpec  # noqa: F401
from ..jit import save_load as _sl


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    raise NotImplementedError(
        "program-based save is not part of the TPU stack; use "
        "paddle_tpu.jit.save(layer, path, input_spec=[...]) — same artifact")


def load_inference_model(path_prefix, executor=None, **kwargs):
    layer = _sl.load(path_prefix)
    return layer


__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]
