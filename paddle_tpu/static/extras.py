"""Static-surface extras: compiled-program shims, EMA, weight-norm attr,
vendor stubs.

Reference: python/paddle/static/__init__.py exports. BuildStrategy /
CompiledProgram configure the reference's graph-optimization passes —
on this stack XLA owns those passes, so they are accepted-and-recorded
config objects whose Program runs unchanged (the one real knob,
fuse-ops, is always on in XLA). ExponentialMovingAverage is the real
reference utility (python/paddle/static/nn/metric.py ExponentialMovingAverage
analog at python/paddle/incubate/... — static/__init__ re-exports it from
paddle.static); implemented over concrete Parameters.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .program import current_program, default_main_program


class BuildStrategy:
    """Attribute-bag parity shim (reference:
    paddle/fluid/framework/details/build_strategy.h bound via pybind).
    Every knob defaults to the reference default and is recorded; XLA's
    pipeline replaces the pass list, so the knobs do not re-route
    compilation on this stack."""

    def __init__(self):
        self.enable_inplace = True
        self.enable_addto = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = True
        self.fuse_gemm_epilogue = False
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.memory_optimize = None
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.debug_graphviz_path = ""
        self.build_cinn_pass = False

    def __repr__(self):
        on = [k for k, v in vars(self).items() if v is True]
        return f"BuildStrategy({', '.join(on) or 'defaults'})"


class CompiledProgram:
    """Wrapper marking a Program for "compiled" execution (reference:
    python/paddle/static/compiler.py CompiledProgram). Executor.run
    unwraps it; the replay already executes per-op under XLA, and
    whole-program compilation is paddle.jit.to_static's job."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        # reference legacy API: multi-card graph replication. Sharding on
        # this stack is mesh-based (paddle.distributed); accept + record.
        if build_strategy is not None:
            self._build_strategy = build_strategy
        return self


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference:
    python/paddle/static/nn/common.py ExponentialMovingAverage):
    ``update()`` after each optimizer step; ``apply(exe)`` context swaps
    the shadow values in (and restores on exit unless need_restore=False).

    Applies over the current Program's concrete Parameters (or an
    explicit ``parameter_list``) — the reference walks the program's
    parameter variables the same way.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameter_list=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._params = list(parameter_list) if parameter_list else None
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def _param_list(self):
        if self._params is None:
            prog = current_program() or default_main_program()
            self._params = prog.parameters()
        return self._params

    def update(self):
        """shadow = decay * shadow + (1 - decay) * param, with the
        reference's thres_steps-style dynamic decay ramp
        (min(decay, (1+step)/(10+step)))."""
        self._step += 1
        decay = min(self._decay, (1.0 + self._step) / (10.0 + self._step)) \
            if self._thres_steps is not None else self._decay
        for p in self._param_list():
            key = id(p)
            cur = p._data
            if key not in self._shadow:
                self._shadow[key] = cur
            else:
                self._shadow[key] = (decay * self._shadow[key]
                                     + (1.0 - decay) * cur)

    class _Apply:
        def __init__(self, ema, need_restore):
            self.ema = ema
            self.need_restore = need_restore

        def __enter__(self):
            ema = self.ema
            for p in ema._param_list():
                if id(p) in ema._shadow:
                    ema._backup[id(p)] = p._data
                    p._data = jnp.asarray(ema._shadow[id(p)],
                                          dtype=p._data.dtype)
            return ema

        def __exit__(self, *exc):
            ema = self.ema
            if self.need_restore:
                for p in ema._param_list():
                    if id(p) in ema._backup:
                        p._data = ema._backup[id(p)]
            ema._backup = {}
            return False

    def apply(self, executor=None, need_restore=True):
        return self._Apply(self, need_restore)

    def restore(self, executor=None):
        for p in self._param_list():
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}


from ..nn.layer.layers import ParamAttr as _ParamAttr  # noqa: E402


class WeightNormParamAttr(_ParamAttr):
    """ParamAttr requesting weight-norm reparameterization (reference:
    python/paddle/static/param_attr.py WeightNormParamAttr). On this
    stack the reparameterization itself is applied with
    ``paddle.nn.utils.weight_norm`` on the constructed Layer; the attr
    carries ``dim`` so porting code type-checks and documents intent."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         need_clip=need_clip)
        self.do_model_average = do_model_average
        self.dim = dim


# -- vendor (Graphcore IPU) stubs: sanctioned descope ----------------------

class IpuStrategy:
    """IPU vendor backend is not part of this stack (SURVEY.md §2.4:
    single-accelerator TPU build; XPU/IPU/NPU backends are sanctioned
    descopes). Constructing the strategy object is allowed so configs
    parse; attaching it to execution raises."""

    def __init__(self):
        self._config = {}

    def set_graph_config(self, **kwargs):
        self._config.update(kwargs)

    def set_pipelining_config(self, **kwargs):
        self._config.update(kwargs)

    def set_precision_config(self, **kwargs):
        self._config.update(kwargs)


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        raise RuntimeError(
            "IPU backend is not available on this stack (TPU build; "
            "sanctioned vendor descope — SURVEY.md §2.4)")


def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError(
        "IPU backend is not available on this stack (TPU build; "
        "sanctioned vendor descope — SURVEY.md §2.4)")


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: python/paddle/tensor/creation.py create_global_var —
    a persistable filled tensor living outside any program."""
    from ..tensor.creation import full
    t = full(shape, value, dtype=dtype)
    t.persistable = persistable
    if name:
        t.name = name
    return t


class device_guard:
    """reference: base/framework.py device_guard — op device placement
    context. PJRT owns placement on this stack; the context records the
    request for API parity and is a no-op."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Batch top-k accuracy op (reference: static/nn/metric.py:36)."""
    import jax.numpy as jnp
    from ..core.dispatch import op_call

    def _body(x, lbl, *, k):
        topk_idx = jnp.argsort(-x, axis=-1)[:, :k]
        hit = jnp.any(topk_idx == lbl.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return op_call("accuracy", _body, input, label, k=int(k))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Batch AUC op (reference: static/nn/metric.py:101). Returns
    (auc_out, batch_auc_out, [stat tensors]) like the reference; the
    stats are the histogram buckets this batch contributes."""
    import jax.numpy as jnp
    from ..core.dispatch import op_call

    def _body(x, lbl, *, nt):
        pos_prob = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else \
            x.reshape(x.shape[0], -1)[:, -1]
        bucket = jnp.clip((pos_prob * nt).astype(jnp.int32), 0, nt)
        lblf = lbl.reshape(-1)
        pos = jnp.zeros(nt + 1).at[bucket].add(lblf.astype(jnp.float32))
        neg = jnp.zeros(nt + 1).at[bucket].add(1.0 - lblf.astype(
            jnp.float32))
        # trapezoid over descending threshold
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tot_pos = tp[-1]
        tot_neg = fp[-1]
        tpr = tp / jnp.maximum(tot_pos, 1.0)
        fpr = fp / jnp.maximum(tot_neg, 1.0)
        a = jnp.trapezoid(tpr, fpr)
        return a, pos, neg

    a, pos, neg = op_call("auc", _body, input, label,
                          nt=int(num_thresholds))
    return a, a, [pos, neg]


def cuda_places(device_ids=None):
    """reference: base/framework.py cuda_places. This stack's
    accelerator is the TPU — returns the accelerator places so ported
    device-list code sees the real devices (CUDAPlace does not exist
    here)."""
    from ..core.place import _accelerators, _cpus, Place
    devs = _accelerators() or _cpus()
    if device_ids is not None:
        devs = [devs[i] for i in device_ids]
    return [Place("cpu" if d.platform == "cpu" else "tpu", d.id)
            for d in devs]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def set_ipu_shard(layer, index=-1, stage=-1):
    raise RuntimeError(
        "IPU backend is not available on this stack (TPU build; "
        "sanctioned vendor descope — SURVEY.md §2.4)")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle is parameter-server-tier (sanctioned descope, "
        "SURVEY.md §7); compute CTR metrics with paddle.metric.Auc")
