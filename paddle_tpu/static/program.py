"""Program / Executor — the reference's static-graph surface.

Reference: python/paddle/static/ (Program `base/framework.py:5940`,
Executor `base/executor.py:812`, `static.data` `static/input.py:30`,
program_guard `base/framework.py:7450`). On this stack a Program is a
recorded op list: under static mode, any op touching a symbolic
``Variable`` is captured at the dispatch layer (core/dispatch.op_call)
with its pure body and argument tree instead of executing; shapes/dtypes
propagate via ``jax.eval_shape``. ``Executor.run`` replays the recording
through the SAME eager op layer on the fed arrays — so autograd, AMP,
kernel overrides, and optimizer updates behave exactly as in dygraph —
and XLA compiles the replayed computation per op (`to_static` remains
the whole-program-compile path; reference CINN plays that role).

Static TRAINING works through ``Optimizer.minimize(loss)`` recorded on
the Program: each ``Executor.run`` replays forward, runs the eager tape
backward from the loss, and applies the optimizer — parameter state
lives in the concrete Parameter tensors shared with the Layers that
created them (the reference's scope variables).
"""
from __future__ import annotations

import threading

import numpy as np

import jax

from ..core.tensor import Tensor
from ..core import dispatch as _dispatch


class Variable(Tensor):
    """Symbolic graph variable: a Tensor whose ``_data`` is a
    ``jax.ShapeDtypeStruct`` (shape/dtype flow through every Tensor
    property; any attempt to compute on it eagerly is intercepted by the
    recording dispatch)."""

    def __init__(self, name, shape, dtype, stop_gradient=True):
        from ..core.dtype import to_jax_dtype
        shape = [0 if s is None else (s if s >= 0 else 0) for s in shape]
        self._data = jax.ShapeDtypeStruct(tuple(shape), to_jax_dtype(dtype))
        self.name = name
        self.stop_gradient = stop_gradient
        # full Tensor attribute contract (core/tensor.py __init__)
        self.grad = None
        self._grad_node = None
        self._output_slot = 0
        self.persistable = False
        self._grad_hooks = []

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={list(self._data.shape)}, "
                f"dtype={self._data.dtype})")


class _Node:
    __slots__ = ("op_name", "fn", "args", "kwargs", "outs")

    def __init__(self, op_name, fn, args, kwargs, outs):
        self.op_name = op_name
        self.fn = fn
        self.args = args        # original tree; Variables mark graph edges
        self.kwargs = kwargs
        self.outs = outs        # flat list of output Variables


class Program:
    """A recorded op sequence (reference Program; single global block)."""

    def __init__(self):
        self._nodes: list[_Node] = []
        self._feeds: dict[str, Variable] = {}
        self._minimize = None    # (optimizer, loss Variable)
        self._backward = None    # (loss Variable, [(param, grad Var)])
        self._grad_requests = []  # (targets, inputs, grad Vars)
        self.random_seed = 0

    # -- reference API ----------------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._nodes = list(self._nodes)
        p._feeds = dict(self._feeds)
        p._grad_requests = list(self._grad_requests)
        if not for_test:
            p._minimize = self._minimize
            p._backward = self._backward
        else:
            # reference clone(for_test=True) switches train-mode ops to
            # eval: drop training flags and zero dropout rates
            rewritten = []
            for node in p._nodes:
                kw = dict(node.kwargs)
                if "training" in kw:
                    kw["training"] = False
                if "dropout" in node.op_name and "p" in kw:
                    kw["p"] = 0.0
                rewritten.append(_Node(node.op_name, node.fn, node.args,
                                       kw, node.outs))
            p._nodes = rewritten
        return p

    def parameters(self):
        """Concrete trainable Parameters referenced by recorded nodes."""
        seen, out = set(), []
        for node in self._nodes:
            flat = jax.tree.leaves(
                (node.args, node.kwargs),
                is_leaf=lambda x: isinstance(x, Tensor))
            for t in flat:
                if isinstance(t, Tensor) and not isinstance(t, Variable) \
                        and not t.stop_gradient and id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def state_dict(self, mode="all"):
        return {getattr(p, "name", f"param_{i}"): p
                for i, p in enumerate(self.parameters())}

    def __repr__(self):
        return f"Program(nodes={len(self._nodes)}, feeds={list(self._feeds)})"


class _BuilderState(threading.local):
    def __init__(self):
        self.static_mode = False
        self.stack: list[Program] = []


_state = _BuilderState()
_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


def set_default_main_program(p):
    global _default_main
    _default_main = p


def enable_static_mode(flag=True):
    _state.static_mode = flag
    if flag:
        # install the dispatch hook once; it stays (one None check is the
        # dynamic-mode cost, and static_mode gates the rest)
        _dispatch._static_state = _state


def in_static_mode():
    return _state.static_mode


def current_program():
    if _state.stack:
        return _state.stack[-1]
    return _default_main


class program_guard:
    """``with static.program_guard(main, startup):`` — records into
    ``main`` (startup is accepted for API parity; parameter init runs
    eagerly at Layer construction on this stack)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _state.stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _state.stack.pop()
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """``static.data`` (reference: static/input.py:30): declare a feed."""
    v = Variable(name, shape, dtype)
    prog = current_program()
    prog._feeds[name] = v
    return v


# -- recording dispatch hook ---------------------------------------------

_NOT_RECORDED = object()


def maybe_record(op_name, fn, default_fn, args, kwargs):
    """Called from core.dispatch.op_call when static mode is on: if any
    input is symbolic, record the op into the current Program and return
    symbolic outputs (shape/dtype via jax.eval_shape).

    The node stores ``default_fn`` (not the currently-resolved override):
    Executor.run replays through ``op_call``, which re-resolves overrides
    from the live registry — preserving the NotImplementedError kernel
    fallback at replay exactly as in eager mode.
    """
    if not _state.static_mode:
        return _NOT_RECORDED
    flat, treedef = jax.tree.flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    if not any(isinstance(x, Variable) for x in flat):
        return _NOT_RECORDED
    # only the symbolic leaves become eval_shape arguments; settings
    # (bools/ints) and concrete tensors ride in the closure so python
    # control flow over them stays concrete
    sym_idx = [i for i, x in enumerate(flat) if isinstance(x, Variable)]
    base = [x._data if isinstance(x, Tensor) and not isinstance(x, Variable)
            else x for x in flat]

    def shape_fn_of(body):
        def shape_fn(*sym):
            vals = list(base)
            for i, s in zip(sym_idx, sym):
                vals[i] = s
            a, kw = jax.tree.unflatten(treedef, vals)
            return body(*a, **kw)
        return shape_fn

    sym_avals = [flat[i]._data for i in sym_idx]
    try:
        out_shapes = jax.eval_shape(shape_fn_of(fn), *sym_avals)
    except NotImplementedError:
        # overridden kernel declined these inputs — same fallback rule as
        # eager dispatch (FLAGS_enable_api_kernel_fallback)
        from ..core.flags import GLOBAL_FLAGS
        if fn is default_fn \
                or not GLOBAL_FLAGS.get("enable_api_kernel_fallback"):
            raise
        out_shapes = jax.eval_shape(shape_fn_of(default_fn), *sym_avals)
    out_flat, out_tree = jax.tree.flatten(out_shapes)
    prog = current_program()
    outs = [Variable(f"{op_name}_{len(prog._nodes)}.{i}", s.shape, s.dtype,
                     stop_gradient=False)
            for i, s in enumerate(out_flat)]
    prog._nodes.append(_Node(op_name, default_fn, args, kwargs, outs))
    wrapped = jax.tree.unflatten(out_tree, outs)
    return wrapped


# -- scope ----------------------------------------------------------------

class _VarHandle:
    def __init__(self, value):
        self._value = value

    def get_tensor(self):
        return np.asarray(self._value)


class Scope:
    """Name -> value map (reference: paddle/fluid/framework/scope.h via
    global_scope); Executor publishes feeds, fetches, and parameters."""

    def __init__(self):
        self._vars: dict[str, object] = {}

    def var(self, name):
        self._vars.setdefault(name, None)
        return _VarHandle(self._vars[name])

    def find_var(self, name):
        if name not in self._vars:
            return None
        return _VarHandle(self._vars[name])

    def set(self, name, value):
        self._vars[name] = value


_global_scope = Scope()
_scope_stack: list[Scope] = []


def global_scope():
    return _scope_stack[-1] if _scope_stack else _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False


# -- executor -------------------------------------------------------------

class Executor:
    """Replays a Program through the eager op layer (reference:
    base/executor.py:812 — feed/fetch run loop). ``place`` is accepted
    for parity; arrays live where PJRT puts them."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, scope=None):
        program = program if program is not None else _default_main
        if hasattr(program, "_program"):   # CompiledProgram wrapper
            program = program._program
        if program is _default_startup or not program._nodes:
            return []  # startup: parameter init already ran eagerly
        feed = feed or {}
        scope = scope or global_scope()
        env: dict[int, Tensor] = {}
        missing = [n for n in program._feeds if n not in feed]
        if missing:
            raise ValueError(f"Executor.run: missing feeds {missing}")
        grad_inputs = {id(v) for _, ins, _ in program._grad_requests
                       for v in ins}
        for name, var in program._feeds.items():
            t = Tensor(jax.numpy.asarray(feed[name]))
            if id(var) in grad_inputs:
                t.stop_gradient = False
            env[id(var)] = t
            scope.set(name, t._data)

        was_static = _state.static_mode
        _state.static_mode = False   # replay must EXECUTE, not re-record
        try:
            def realize(x):
                if isinstance(x, Variable):
                    if id(x) not in env:
                        raise RuntimeError(
                            f"Variable {x.name} used before definition")
                    return env[id(x)]
                return x

            for node in program._nodes:
                a, kw = jax.tree.map(
                    realize, (node.args, node.kwargs),
                    is_leaf=lambda x: isinstance(x, Tensor))
                out = _dispatch.op_call(node.op_name, node.fn, *a, **kw)
                out_flat = out if isinstance(out, (list, tuple)) else [out]
                out_flat = [o for o in jax.tree.leaves(
                    out_flat, is_leaf=lambda x: isinstance(x, Tensor))]
                for var, val in zip(node.outs, out_flat):
                    env[id(var)] = val

            def _realized(v, role):
                if not isinstance(v, Variable):
                    return v          # concrete Tensor (e.g. a Parameter)
                t = env.get(id(v))
                if t is None:
                    raise RuntimeError(
                        f"gradients(): {role} Variable "
                        f"{getattr(v, 'name', v)!r} was not produced by "
                        "this program's replay")
                return t

            for targets, inputs, grad_vars in program._grad_requests:
                from ..autograd import grad as _grad
                tgt = [_realized(v, "target") for v in targets]
                ins = [_realized(v, "input") for v in inputs]
                gs = _grad(tgt, ins, retain_graph=True,
                           allow_unused=True)
                for gv, g in zip(grad_vars, gs):
                    env[id(gv)] = g if g is not None else Tensor(
                        jax.numpy.zeros(gv.shape, gv._data.dtype))

            loss_to_backward = None
            if program._minimize is not None:
                opt, loss_var = program._minimize
                loss_to_backward = (loss_var, None)
            elif program._backward is not None:
                loss_to_backward = program._backward

            if loss_to_backward is not None:
                loss_var = loss_to_backward[0]
                loss = env.get(id(loss_var))
                if loss is None:
                    raise RuntimeError(
                        "backward loss not produced by replay")
                # each run() computes THIS run's grads (the reference's
                # executor scope is fresh per run) — drop any grads left
                # from a previous run without an optimizer clear
                for p in program.parameters():
                    p.grad = None
                loss.backward()
                if program._backward is not None:
                    for param, gv in program._backward[1]:
                        env[id(gv)] = param.grad if param.grad is not None \
                            else Tensor(jax.numpy.zeros(
                                param.shape, param._data.dtype))
                if program._minimize is not None:
                    opt = program._minimize[0]
                    opt.step()
                    opt.clear_grad()

            results = []
            by_name = None
            for f in (fetch_list or []):
                if isinstance(f, str):
                    # reference idiom: fetch by variable name
                    if by_name is None:
                        by_name = {v.name: v for node in program._nodes
                                   for v in node.outs}
                        by_name.update(program._feeds)
                        for _, _, gvs in program._grad_requests:
                            by_name.update({g.name: g for g in gvs})
                        if program._backward is not None:
                            by_name.update(
                                {g.name: g
                                 for _, g in program._backward[1]})
                    if f not in by_name:
                        raise ValueError(f"fetch target {f!r}: no variable "
                                         f"of that name in the program")
                    f = by_name[f]
                t = env.get(id(f))
                if t is None:
                    raise ValueError(f"fetch target {f!r} was not computed")
                results.append(np.asarray(t._data) if return_numpy else t)
            return results
        finally:
            _state.static_mode = was_static

    def close(self):
        return None


def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def save(program, model_prefix):
    """Persist a Program's parameters (reference: static/io.py save)."""
    from ..framework.io import save as fsave
    state = {k: v for k, v in program.state_dict().items()}
    fsave({"state_dict": {k: t for k, t in state.items()},
           "format": "paddle_tpu.static.v1"}, model_prefix + ".pdparams")


def load(program, model_prefix, executor=None, var_list=None):
    from ..framework.io import load as fload
    blob = fload(model_prefix + ".pdparams")
    state = blob.get("state_dict", blob)
    params = program.state_dict()
    for name, p in params.items():
        if name in state:
            src = state[name]
            arr = src._data if isinstance(src, Tensor) else jax.numpy.asarray(
                np.asarray(src))
            p._inplace_update(arr.astype(p._data.dtype))


# -- static autodiff surface ----------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Record backward-from-``loss`` on the current Program (reference:
    python/paddle/base/backward.py:1967). Replay runs the eager tape
    backward after the forward nodes; returns ``[(param, grad_var)]``
    pairs whose grad Variables are fetchable by name (``<param>@GRAD``).
    """
    prog = current_program()
    params = parameter_list if parameter_list is not None \
        else prog.parameters()
    if no_grad_set:
        # the reference accepts Parameter objects OR their name strings
        drop_ids = {id(p) for p in no_grad_set if not isinstance(p, str)}
        drop_names = {p for p in no_grad_set if isinstance(p, str)}
        params = [p for p in params
                  if id(p) not in drop_ids
                  and getattr(p, "name", None) not in drop_names]
    pairs = []
    for i, p in enumerate(params):
        name = getattr(p, "name", None) or f"param_{i}"
        gv = Variable(f"{name}@GRAD", list(p.shape), str(p._data.dtype),
                      stop_gradient=True)
        pairs.append((p, gv))
    prog._backward = (loss, pairs)
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None,
              name=None):
    """Record grads of ``targets`` w.r.t. ``inputs`` (reference:
    python/paddle/base/backward.py gradients): replay computes them with
    ``paddle.autograd.grad`` over the realized tensors. Returns one grad
    Variable per input, fetchable like any output."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None:
        raise NotImplementedError(
            "gradients(target_gradients=...) is not supported; seed grads "
            "default to ones as in the reference's common path")
    prog = current_program()
    gvs = [Variable(f"{getattr(v, 'name', f'x_{i}')}@GRAD",
                    list(v.shape), str(v._data.dtype), stop_gradient=True)
           for i, v in enumerate(inputs)]
    prog._grad_requests.append((list(targets), list(inputs), gvs))
    return gvs


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Record an arbitrary host-Python op (reference: static/nn/common.py
    py_func): ``out`` declares the result shapes (the reference requires
    pre-created out vars for the same reason — no shape inference through
    host code). ``backward_func`` is unsupported: replay runs through the
    eager tape, so differentiable host ops belong in a PyLayer."""
    if backward_func is not None:
        raise NotImplementedError(
            "py_func(backward_func=...): wrap host code in a PyLayer for "
            "gradients on this stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    prog = current_program()
    # ``out`` vars are shape DECLARATIONS (usually made with static.data,
    # the only public Variable constructor) — they are produced by this
    # node, not fed, so unregister them from the feed list
    out_ids = {id(ov) for ov in outs}
    for name in [n for n, v in prog._feeds.items() if id(v) in out_ids]:
        del prog._feeds[name]

    def _body(*arrays):
        res = func(*[Tensor(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        vals = []
        for r, ov in zip(res, outs):
            a = r._data if isinstance(r, Tensor) else jax.numpy.asarray(
                np.asarray(r))
            vals.append(a.astype(ov._data.dtype))
        return vals[0] if len(vals) == 1 else tuple(vals)

    node_outs = [Variable(f"py_func_{len(prog._nodes)}.{i}",
                          list(ov.shape), str(ov._data.dtype),
                          stop_gradient=True)
                 for i, ov in enumerate(outs)]
    prog._nodes.append(_Node("py_func", _body, tuple(xs), {}, node_outs))
    return node_outs[0] if len(node_outs) == 1 else node_outs


class name_scope:
    """Cosmetic op-name prefix context (reference:
    base/framework.py name_scope); recorded names are not prefixed on
    this stack — the context exists for API/indentation parity."""

    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug-print pass-through op (reference: static/nn/control_flow.py
    Print). Prints at replay (concrete values); silent while recording
    (abstract values)."""
    state = {"n": 0}

    def _body(a):
        from jax.core import Tracer
        concrete = not isinstance(a, (jax.ShapeDtypeStruct, Tracer))
        if concrete and (first_n < 0 or state["n"] < first_n):
            state["n"] += 1
            head = message or "Print"
            body = np.array2string(np.asarray(a), threshold=summarize)
            print(f"{head}: shape={list(a.shape)} dtype={a.dtype}\n{body}")
        return a

    from ..core.dispatch import op_call
    return op_call("print", _body, input)
