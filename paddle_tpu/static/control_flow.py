"""Data-dependent control flow ops (reference:
python/paddle/static/nn/control_flow.py while_loop:755 / cond / case /
switch_case).

TPU-native design: these ARE ``lax.while_loop`` / ``lax.cond`` /
``lax.switch`` with Tensor wrappers — the loop/branch compiles ONCE and
the trip count / branch choice is decided on-device at run time. This is
the O(1)-trace path for data-dependent decode loops (round-3 verdict
item 5): a ``while bool(t):`` Python loop needs one specialization per
trip count under SOT-lite value guards, while ``while_loop`` here needs
exactly one trace for all trip counts.

XLA discipline (same as the reference's static-graph contract): loop
variables must keep their shapes and dtypes across iterations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd as _ag
from ..core.tensor import Tensor

__all__ = ["while_loop", "cond", "case", "switch_case"]


def _is_tensor(x):
    return isinstance(x, Tensor)


def _flatten(tree):
    return jax.tree.flatten(tree, is_leaf=_is_tensor)


def _to_arrays(flat):
    return [x._data if _is_tensor(x) else jnp.asarray(x) for x in flat]


def _scalar_pred(p):
    a = p._data if _is_tensor(p) else jnp.asarray(p)
    return jnp.reshape(a, ()).astype(bool)


def _loop_fns(cond, body, tree):
    """(cond, body) over Tensor trees -> (c, b) over flat array lists."""
    def c(arrs):
        vars_ = jax.tree.unflatten(tree, [Tensor(a) for a in arrs])
        with _ag.no_grad():
            return _scalar_pred(cond(*vars_))

    def b(arrs):
        vars_ = jax.tree.unflatten(tree, [Tensor(a) for a in arrs])
        with _ag.no_grad():
            out = body(*vars_)
        if not isinstance(out, (list, tuple)):
            out = [out]
        flat_o, _tree_o = _flatten(list(out))
        arrs_o = _to_arrays(flat_o)
        if len(arrs_o) != len(arrs):
            raise ValueError(
                f"while_loop body returned {len(arrs_o)} vars, expected "
                f"{len(arrs)} (loop_vars structure must be preserved)")
        for i, (a_new, a_old) in enumerate(zip(arrs_o, arrs)):
            if a_new.shape != a_old.shape or a_new.dtype != a_old.dtype:
                raise ValueError(
                    f"while_loop var {i} changed from "
                    f"{a_old.shape}/{a_old.dtype} to "
                    f"{a_new.shape}/{a_new.dtype}; loop variables must be "
                    "shape/dtype-invariant (pad to a static bound)")
        return arrs_o

    return c, b


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """Run ``body`` while ``cond`` holds (reference:
    static/nn/control_flow.py:755).

    cond(*loop_vars) -> scalar bool Tensor; body(*loop_vars) -> new
    loop_vars (same structure, shapes and dtypes). Compiles to ONE
    ``lax.while_loop`` — the trip count is data-dependent on device, so a
    decode loop traces once for every sequence. Works eagerly and under
    ``paddle.jit.to_static``.

    Gradient semantics (the reference's while_grad op capability):
    without a bound, XLA's while is not reverse-differentiable and
    gradients do not flow. Pass ``maximum_trip_count`` to get the
    TPU-native differentiable form: a ``lax.scan`` over the bound with
    predicated carries — iterations past the condition's first False
    keep the state unchanged (and are dead FLOPs, the price of a static
    schedule), and the whole loop records on the autograd tape.

    Gradients flow to the LOOP VARS: any tensor that needs a gradient
    (weights included) must be passed through ``loop_vars`` and returned
    by ``body`` (unchanged is fine) — a tensor captured in the closures
    enters the compiled loop as a constant, exactly like the reference's
    while block, whose differentiable externals become block inputs.
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("loop_vars must be a non-empty list/tuple")
    flat, tree = _flatten(list(loop_vars))
    if maximum_trip_count is not None:
        from ..core.dispatch import op_call
        n_steps = int(maximum_trip_count)

        def pure(*arrs):
            c, b = _loop_fns(cond, body, tree)

            def step(carry, _):
                keep = c(carry)
                new = b(carry)
                merged = [jnp.where(keep, n, o)
                          for n, o in zip(new, carry)]
                return merged, None

            out, _ = jax.lax.scan(step, list(arrs), None, length=n_steps)
            return tuple(out)

        tensors = [x if _is_tensor(x) else Tensor(jnp.asarray(x))
                   for x in flat]
        res = op_call("while_loop_bounded", pure, *tensors,
                      _transient=True)
        if not isinstance(res, (list, tuple)):
            res = (res,)
        return jax.tree.unflatten(tree, list(res))
    init = _to_arrays(flat)
    c, b = _loop_fns(cond, body, tree)
    res = jax.lax.while_loop(c, b, init)
    out = jax.tree.unflatten(tree, [Tensor(r) for r in res])
    return out


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Two-way branch (reference: static/nn/control_flow.py cond).

    Eager with a concrete pred: runs the chosen closure directly (the
    reference's dygraph behavior). Traced: both closures are traced and
    ``lax.cond`` selects on device — output structures/shapes must match.
    """
    p = _scalar_pred(pred)
    if not isinstance(p, jax.core.Tracer):
        fn = true_fn if bool(p) else false_fn
        return fn() if fn is not None else None
    if true_fn is None or false_fn is None:
        raise ValueError("traced cond requires both true_fn and false_fn")

    def run(fn):
        with _ag.no_grad():
            out = fn()
        flat, tree = _flatten(out)
        return _to_arrays(flat), tree

    # trace once outside lax.cond to learn the output tree, then again
    # inside (cheap: tracing only), so both branches return matched flats
    _, tree_t = run(true_fn)

    res = jax.lax.cond(p,
                       lambda _: run(true_fn)[0],
                       lambda _: run(false_fn)[0],
                       None)
    return jax.tree.unflatten(tree_t, [Tensor(r) for r in res])


def case(pred_fn_pairs, default=None, name=None):
    """First-match multiway branch (reference: control_flow.py case):
    ``[(pred, fn), ...]`` evaluated in order; ``default`` when none hold."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    (pred, fn), *rest = list(pred_fn_pairs)
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default=default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Indexed dispatch (reference: control_flow.py switch_case) —
    ``lax.switch`` on device when traced. branch_fns: dict {int: fn} or
    list of (int, fn) / fn."""
    if isinstance(branch_fns, (list, tuple)):
        if all(callable(f) for f in branch_fns):
            pairs = list(enumerate(branch_fns))
        else:
            pairs = [(int(k), f) for k, f in branch_fns]
    else:
        pairs = sorted((int(k), f) for k, f in branch_fns.items())
    keys = [k for k, _ in pairs]
    fns = [f for _, f in pairs]
    idx = branch_index._data if _is_tensor(branch_index) \
        else jnp.asarray(branch_index)
    idx = jnp.reshape(idx, ()).astype(jnp.int32)
    if default is None:
        default = fns[-1]

    if not isinstance(idx, jax.core.Tracer):
        i = int(idx)
        fn = dict(pairs).get(i, default)
        return fn()

    # map sparse keys onto dense lax.switch branches; unknown -> default
    def run(fn):
        with _ag.no_grad():
            out = fn()
        flat, tree = _flatten(out)
        return _to_arrays(flat), tree

    _, tree_t = run(fns[0])
    table = {k: i for i, k in enumerate(keys)}
    dense = jnp.full((max(keys) + 1,), len(fns), jnp.int32)
    for k, i in table.items():
        dense = dense.at[k].set(i)
    # any out-of-range index — negative included — dispatches to default,
    # matching the eager dict.get path
    in_range = (idx >= 0) & (idx <= max(keys))
    sel = jnp.where(in_range, dense[jnp.clip(idx, 0, max(keys))],
                    jnp.asarray(len(fns), jnp.int32))
    branches = [(lambda f: (lambda _: run(f)[0]))(f) for f in fns]
    branches.append(lambda _: run(default)[0])
    res = jax.lax.switch(sel, branches, None)
    return jax.tree.unflatten(tree_t, [Tensor(r) for r in res])
