"""Static Program serialization (reference: python/paddle/static/io.py —
serialize_program, serialize_persistables, normalize_program,
save_to_file/load_from_file, load/set_program_state).

The reference serializes ProgramDesc protobufs. Here the recorded
Program is lowered ONCE through jax.export: the replay (the exact node
list Executor.run executes) is traced into a StableHLO artifact with
the parameters captured as constants — the same portable-XLA form the
jit artifacts use (jit/save_load.py). Persistables serialize separately
as a name->array blob so programs and weights can move independently.
"""
from __future__ import annotations

import io
import pickle

import numpy as np

import jax
from jax import export as jax_export

from ..core.tensor import Tensor
from ..core import dispatch as _dispatch
from .program import Program, Variable, current_program, _state


def _feed_fetch(program, feed_vars, fetch_vars):
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    names = []
    for v in feed_vars:
        matches = [n for n, fv in program._feeds.items() if fv is v]
        names.append(matches[0] if matches else getattr(v, "name", None))
    return feed_vars, list(fetch_vars), names


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune the program to the nodes that (transitively) produce
    ``fetch_vars`` (reference: static/io.py normalize_program — dead-op
    elimination before serialization)."""
    feed_vars, fetch_vars, _ = _feed_fetch(program, feed_vars, fetch_vars)
    needed = {id(v) for v in fetch_vars}
    kept = []
    for node in reversed(program._nodes):
        if any(id(o) in needed for o in node.outs):
            kept.append(node)
            flat = jax.tree.leaves((node.args, node.kwargs),
                                   is_leaf=lambda x: isinstance(x, Tensor))
            for t in flat:
                if isinstance(t, Variable):
                    needed.add(id(t))
    out = Program()
    out._nodes = list(reversed(kept))
    feed_ids = {id(f) for f in feed_vars}
    out._feeds = {n: v for n, v in program._feeds.items()
                  if id(v) in needed or id(v) in feed_ids}
    out.random_seed = program.random_seed
    return out


def _replay_pure(program, feed_vars, fetch_vars):
    """The Executor.run node walk as a pure function of the feeds."""
    def fn(*feeds):
        from ..core.autograd import no_grad
        env = {id(v): Tensor(arr) for v, arr in zip(feed_vars, feeds)}

        def realize(x):
            if isinstance(x, Variable):
                return env[id(x)]
            return x

        was = _state.static_mode
        _state.static_mode = False
        try:
            with no_grad():
                for node in program._nodes:
                    a, kw = jax.tree.map(
                        realize, (node.args, node.kwargs),
                        is_leaf=lambda x: isinstance(x, Tensor))
                    out = _dispatch.op_call(node.op_name, node.fn, *a, **kw)
                    flat = jax.tree.leaves(
                        out if isinstance(out, (list, tuple)) else [out],
                        is_leaf=lambda x: isinstance(x, Tensor))
                    for var, val in zip(node.outs, flat):
                        env[id(var)] = val
        finally:
            _state.static_mode = was
        return tuple(env[id(f)]._data for f in fetch_vars)
    return fn


_SER_MAGIC = b"PTPU-STATIC-PROGRAM-v1\n"


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Program -> portable bytes (StableHLO via jax.export; parameters
    baked as constants — the inference form, like the reference's pruned
    ProgramDesc)."""
    program = program or current_program()
    program = normalize_program(program, feed_vars, fetch_vars)
    feed_vars, fetch_vars, feed_names = _feed_fetch(program, feed_vars,
                                                    fetch_vars)
    scope = jax_export.SymbolicScope()
    specs = []
    for i, v in enumerate(feed_vars):
        dims = ",".join(f"b{i}_{j}" if s == 0 else str(int(s))
                        for j, s in enumerate(v._data.shape))
        shape = jax_export.symbolic_shape(dims, scope=scope) if "b" in dims \
            else v._data.shape
        specs.append(jax.ShapeDtypeStruct(shape, v._data.dtype))
    exp = jax_export.export(jax.jit(_replay_pure(program, feed_vars,
                                                 fetch_vars)))(*specs)
    blob = exp.serialize()
    head = pickle.dumps({"feed_names": feed_names,
                         "n_fetch": len(fetch_vars)})
    return _SER_MAGIC + len(head).to_bytes(8, "little") + head + bytes(blob)


class DeserializedProgram:
    """Executable form of serialize_program bytes. Executor.run accepts
    it: feeds are matched by the recorded feed names, fetch_list
    positions index the recorded fetch tuple."""

    def __init__(self, exported, feed_names, n_fetch):
        self._exported = exported
        self.feed_names = feed_names
        self.n_fetch = n_fetch

    def run(self, feed):
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError(f"DeserializedProgram: missing feeds {missing}")
        arrays = [np.asarray(feed[n]) for n in self.feed_names]
        return [np.asarray(x) for x in self._exported.call(*arrays)]


def deserialize_program(data):
    """bytes -> DeserializedProgram (reference: static/io.py
    deserialize_program)."""
    if not data.startswith(_SER_MAGIC):
        raise ValueError("not a paddle_tpu serialized program")
    off = len(_SER_MAGIC)
    hlen = int.from_bytes(data[off:off + 8], "little")
    head = pickle.loads(data[off + 8:off + 8 + hlen])
    exported = jax_export.deserialize(bytearray(data[off + 8 + hlen:]))
    return DeserializedProgram(exported, head["feed_names"],
                               head["n_fetch"])


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """Parameters -> bytes (name -> ndarray blob)."""
    program = program or current_program()
    state = {name: np.asarray(p._data)
             for name, p in program.state_dict().items()}
    return pickle.dumps({"format": "paddle_tpu.persistables.v1",
                         "state": state})


def deserialize_persistables(program, data, executor=None):
    blob = pickle.loads(data)
    state = blob["state"] if isinstance(blob, dict) and "state" in blob \
        else blob
    set_program_state(program, state)
    return program


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    """reference: static/io.py load_program_state — read a .pdparams blob
    into a name->ndarray dict."""
    from ..framework.io import load as fload
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    blob = fload(path)
    state = blob.get("state_dict", blob) if isinstance(blob, dict) else blob
    out = {}
    for k, v in state.items():
        out[k] = np.asarray(v._data) if isinstance(v, Tensor) \
            else np.asarray(v)
    if var_list is not None:
        names = {getattr(v, "name", v) for v in var_list}
        out = {k: v for k, v in out.items() if k in names}
    return out


def set_program_state(program, state_dict):
    """reference: static/io.py set_program_state."""
    import jax.numpy as jnp
    params = program.state_dict()
    for name, p in params.items():
        if name in state_dict:
            src = state_dict[name]
            arr = src._data if isinstance(src, Tensor) else jnp.asarray(
                np.asarray(src))
            p._inplace_update(arr.astype(p._data.dtype))
