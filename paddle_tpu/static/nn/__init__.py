"""paddle.static.nn — the static-graph layer builders.

Reference: python/paddle/static/nn/ (common.py builders, control_flow,
sequence_lod, static_pylayer). Each builder constructs its parameters at
graph-build time through the SAME nn.Layer machinery (the reference's
LayerHelper role) and applies the layer — under ``paddle.enable_static``
the compute records into the current Program; in dygraph it executes
directly. LoD ``sequence_*`` ops are the legacy-LoD tier descoped in
OPS_INVENTORY.md (padded-dense equivalents live in paddle.nn)."""
from __future__ import annotations

import numpy as np

from ..control_flow import (  # noqa: F401
    while_loop, cond, case, switch_case,
)
from ..program import py_func  # noqa: F401
from ...core.tensor import Tensor
from ...nn.layer.layers import ParamAttr


def _act(out, act):
    if act is None:
        return out
    from ... import nn
    return getattr(nn.functional, act)(out)


def _prod(xs):
    p = 1
    for s in xs:
        p *= int(s)
    return p


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py:48 — per-input weight, summed, one
    shared bias."""
    from ... import nn
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    bias = None
    for i, xi in enumerate(xs):
        flat_in = _prod(xi.shape[num_flatten_dims:])
        lin = nn.Linear(flat_in, size,
                        weight_attr=weight_attr,
                        bias_attr=False)
        if len(xi.shape) == num_flatten_dims + 1:
            flat = xi                      # already [*, flat_in]
        else:
            # dynamic leading dims (None -> 0 in a Variable) become -1 so
            # the recorded reshape replays at any batch size
            lead = [int(s) if int(s) > 0 else -1
                    for s in xi.shape[:num_flatten_dims]]
            if lead.count(-1) > 1:
                lead = [-1] + [1] * (len(lead) - 1)
            flat = xi.reshape(lead + [flat_in])
        outs.append(lin(flat))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if bias_attr is not False:
        from ...nn.layer.layers import Parameter
        import jax.numpy as jnp
        b = Parameter(jnp.zeros((size,), dtype=out._data.dtype))
        out = out + b
    return _act(out, activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: static/nn/common.py:3689."""
    from ... import nn
    emb = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                       weight_attr=param_attr)
    return emb(input)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None,
               do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """reference: static/nn/common.py:2613."""
    from ... import nn
    c_axis = 1 if data_layout == "NCHW" else -1
    bn = nn.BatchNorm(int(input.shape[c_axis]), momentum=momentum,
                      epsilon=epsilon, weight_attr=param_attr,
                      bias_attr=bias_attr,
                      data_format="NCHW" if data_layout == "NCHW"
                      else "NHWC",
                      use_global_stats=use_global_stats or None)
    if is_test:
        bn.eval()
    return _act(bn(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: static/nn/common.py:3553 — normalizes over
    dims[begin_norm_axis:]."""
    from ... import nn
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    ln = nn.LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    return _act(ln(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """reference: static/nn/common.py:668."""
    from ... import nn
    c_axis = 1 if data_layout == "NCHW" else -1
    gn = nn.GroupNorm(groups, int(input.shape[c_axis]), epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format="NCHW" if data_layout == "NCHW"
                      else "NHWC")
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference: static/nn/common.py:272."""
    from ... import nn
    cls = {3: nn.InstanceNorm1D, 4: nn.InstanceNorm2D,
           5: nn.InstanceNorm3D}[len(input.shape)]
    inorm = cls(int(input.shape[1]), epsilon=epsilon,
                weight_attr=param_attr, bias_attr=bias_attr)
    return inorm(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference: static/nn/common.py:461 — normalization by accumulated
    batch statistics (batch_size/batch_sum/batch_square_sum accumulators,
    the CTR-model normalizer). The accumulators initialize to the
    reference defaults (count 1e4, zero sum, 1e4 square-sum => unit
    scale), normalize with the PRE-update values, then accumulate this
    batch's count/sum/square-sum — the reference data_norm op's training
    update. Accumulators persist across calls keyed by ``name`` (the
    analog of the reference's per-layer persistable variables, which get
    a unique auto-generated name at build time); UNNAMED calls keep the
    frozen init stats, since distinct unnamed call sites cannot be told
    apart here. Under static (record/replay) mode the accumulation is
    skipped too — the recorded program normalizes with build-time
    stats."""
    import jax
    import jax.numpy as jnp
    from ...nn.layer.layers import Parameter
    c_axis = -1 if data_layout == "NHWC" else 1
    c = int(input.shape[c_axis])
    stat_shape = (c,)
    key = name or moving_mean_name
    stats = _DATA_NORM_STATS.get((key, c)) if key else None
    if stats is None:
        batch_size = Parameter(jnp.full(stat_shape, 1e4, jnp.float32))
        batch_sum = Parameter(jnp.zeros(stat_shape, jnp.float32))
        batch_sq = Parameter(jnp.full(stat_shape, 1e4, jnp.float32))
        for p in (batch_size, batch_sum, batch_sq):
            p.stop_gradient = True
        stats = (batch_size, batch_sum, batch_sq)
        if key:
            _DATA_NORM_STATS[(key, c)] = stats
    batch_size, batch_sum, batch_sq = stats
    mean = batch_sum / batch_size
    scale = (batch_size / batch_sq) ** 0.5
    out = (input - mean) * scale
    # accumulate this batch's stats for subsequent calls — eager named
    # calls only (concrete arrays; static Variables carry ShapeDtypeStruct)
    x = getattr(input, "_data", None)
    if key and isinstance(x, jax.Array):
        red = tuple(i for i in range(x.ndim) if i != c_axis % x.ndim)
        n = 1
        for i in red:
            n *= int(x.shape[i])
        batch_size._data = batch_size._data + float(n)
        batch_sum._data = batch_sum._data + \
            jnp.sum(x, axis=red).astype(jnp.float32)
        batch_sq._data = batch_sq._data + \
            jnp.sum(x * x, axis=red).astype(jnp.float32)
    if enable_scale_and_shift:
        w = Parameter(jnp.ones(stat_shape, jnp.float32))
        b = Parameter(jnp.zeros(stat_shape, jnp.float32))
        out = out * w + b
    return _act(out, act)


# data_norm accumulators: persist across calls (the reference keeps them
# as persistable program variables updated by the op each training step)
_DATA_NORM_STATS: dict = {}


def _conv_nd(input, num_filters, filter_size, stride, padding, dilation,
             groups, param_attr, bias_attr, act, data_format, ndim,
             transpose=False, output_size=None):
    from ... import nn
    chan_axis = 1 if data_format.startswith("NC") else -1
    in_ch = int(input.shape[chan_axis])
    key = ("Conv%dDTranspose" if transpose else "Conv%dD") % ndim
    cls = getattr(nn, key)
    kwargs = dict(stride=stride, padding=padding, dilation=dilation,
                  groups=groups or 1, weight_attr=param_attr,
                  bias_attr=bias_attr, data_format=data_format)
    layer = cls(in_ch, num_filters, filter_size, **kwargs)
    out = layer(input) if not transpose or output_size is None \
        else layer(input, output_size=output_size)
    return _act(out, act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    """reference: static/nn/common.py:780."""
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    data_format, 2)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    """reference: static/nn/common.py:1088."""
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    data_format, 3)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    """reference: static/nn/common.py:1377."""
    assert filter_size is not None or output_size is not None
    if filter_size is None:
        # infer square kernel from output_size (reference rule)
        hw = 2 if data_format == "NCHW" else 1
        i = int(input.shape[hw])
        o = output_size[0] if isinstance(output_size, (list, tuple)) \
            else int(output_size)
        s = stride if isinstance(stride, int) else stride[0]
        p = padding if isinstance(padding, int) else padding[0]
        d = dilation if isinstance(dilation, int) else dilation[0]
        filter_size = (o - (i - 1) * s + 2 * p - 1) // d + 1
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    data_format, 2, transpose=True, output_size=output_size)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    """reference: static/nn/common.py:1753."""
    assert filter_size is not None, \
        "conv3d_transpose: pass filter_size explicitly"
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, param_attr, bias_attr, act,
                    data_format, 3, transpose=True, output_size=output_size)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  modulated=True, name=None):
    """reference: static/nn/common.py:2362 — over vision.ops
    deform_conv2d with build-time-created weight/bias."""
    import jax.numpy as jnp
    from ...nn.layer.layers import Parameter
    from ...vision.ops import deform_conv2d as _dc
    in_ch = int(input.shape[1])
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    fan_in = in_ch * k[0] * k[1]
    w = Parameter(jnp.asarray(
        np.random.default_rng(0).normal(
            0, (2.0 / fan_in) ** 0.5,
            (num_filters, in_ch // groups, k[0], k[1])).astype(np.float32)))
    b = None if bias_attr is False else Parameter(
        jnp.zeros((num_filters,), jnp.float32))
    return _dc(input, offset, w, bias=b,
               mask=mask if modulated else None, stride=stride,
               padding=padding, dilation=dilation,
               deformable_groups=deformable_groups, groups=groups)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: static/nn/common.py:2538 — nn.Bilinear."""
    from ... import nn
    layer = nn.Bilinear(int(x.shape[1]), int(y.shape[1]), size,
                        weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(x, y), act)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: static/nn/common.py:2937 — alpha shape by mode
    (all/channel/element)."""
    import jax.numpy as jnp
    from ...nn.layer.layers import Parameter
    from ...nn.functional import prelu as fprelu
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        c = int(x.shape[1 if data_format == "NCHW" else -1])
        shape = (c,)
    elif mode == "element":
        shape = tuple(int(s) for s in x.shape[1:])
    else:
        raise ValueError(f"prelu mode {mode!r}")
    alpha = Parameter(jnp.full(shape, 0.25, jnp.float32))
    return fprelu(x, alpha, data_format=data_format)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: static/nn/common.py:3331 — lookahead row convolution:
    out[t] = sum_{i=0..k} in[t+i] * W[i] (elementwise over features)."""
    import jax.numpy as jnp
    from ...nn.layer.layers import Parameter
    from ...core.dispatch import eager_apply
    k = int(future_context_size)
    d = int(input.shape[-1])
    w = Parameter(jnp.asarray(np.random.default_rng(0).normal(
        0, d ** -0.5, (k + 1, d)).astype(np.float32)))

    def body(a, wv):
        pad = [(0, 0)] * a.ndim
        pad[-2] = (0, k)
        ap = jnp.pad(a, pad)
        segs = [ap[..., i:i + a.shape[-2], :] * wv[i] for i in range(k + 1)]
        return sum(segs)

    out = eager_apply("row_conv", body, (input, w), {})
    return _act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: static/nn/common.py:3415 — functional weight
    normalization by the top singular value (fresh u/v per call here;
    the stateful form is nn.SpectralNorm / nn.utils.spectral_norm)."""
    import jax.numpy as jnp
    from ...nn.functional import spectral_norm as fsn
    h = int(weight.shape[dim])
    w = _prod(weight.shape) // h
    rng = np.random.default_rng(0)
    u = Tensor(jnp.asarray(rng.normal(size=(h,)).astype(np.float32)))
    v = Tensor(jnp.asarray(rng.normal(size=(w,)).astype(np.float32)))
    return fsn(weight, u, v, dim=dim, power_iters=power_iters, eps=eps)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: static/nn/loss.py:33 — noise-contrastive estimation:
    binary logistic loss on the true class plus ``num_neg_samples``
    sampled noise classes (uniform/custom sampler)."""
    import jax
    import jax.numpy as jnp
    from ...nn.layer.layers import Parameter
    from ...core.dispatch import eager_apply
    from ...core import random as _random
    dim = int(input.shape[-1])
    n_neg = int(num_neg_samples or 10)
    w = Parameter(jnp.asarray(np.random.default_rng(seed or 0).normal(
        0, dim ** -0.5, (num_total_classes, dim)).astype(np.float32)))
    b = None if bias_attr is False else Parameter(
        jnp.zeros((num_total_classes,), jnp.float32))
    key = jax.random.PRNGKey(seed) if seed else _random.next_key()
    if custom_dist is not None:
        probs = jnp.asarray(np.asarray(custom_dist, np.float32))
        neg = jax.random.choice(key, num_total_classes, (n_neg,), p=probs)
    else:
        neg = jax.random.randint(key, (n_neg,), 0, num_total_classes)

    def body(x, lbl, wv, bv, negv):
        lbl = lbl.reshape(-1)
        pos_w = wv[lbl]                       # [B, D]
        s_pos = jnp.sum(x * pos_w, -1)
        neg_w = wv[negv]                      # [K, D]
        s_neg = x @ neg_w.T                   # [B, K]
        if bv is not None:
            s_pos = s_pos + bv[lbl]
            s_neg = s_neg + bv[negv][None, :]
        loss = -jax.nn.log_sigmoid(s_pos) \
               - jnp.sum(jax.nn.log_sigmoid(-s_neg), -1)
        return loss.reshape(-1, 1)

    args = (input, label, w, b, Tensor(neg))
    return eager_apply("nce", body, args, {})


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """reference: static/nn/static_pylayer.py:281 — custom forward with
    an optional custom backward, over the eager PyLayer machinery."""
    from ...autograd import PyLayer
    if backward_fn is None:
        outs = forward_fn(*inputs)
        return outs

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *gs):
            return backward_fn(*gs)

    return _P.apply(*inputs)


def sparse_embedding(*args, **kwargs):
    """reference: static/nn/common.py:3840 — the parameter-server
    distributed lookup table. PS mode is a sanctioned descope
    (SURVEY.md §7); use paddle.nn.Embedding (optionally sharded with
    VocabParallelEmbedding)."""
    raise NotImplementedError(
        "sparse_embedding requires parameter-server mode — sanctioned "
        "descope (SURVEY.md §7); use nn.Embedding / "
        "VocabParallelEmbedding")


def _sequence_stub(opname):
    def stub(*args, **kwargs):
        raise NotImplementedError(
            f"{opname}: legacy LoD sequence ops are descoped "
            "(OPS_INVENTORY.md, legacy-LoD tier); use the padded-dense "
            "equivalents in paddle.nn (Conv1D, softmax with masks, "
            "pooling over masks)")
    stub.__name__ = opname
    return stub


sequence_conv = _sequence_stub("sequence_conv")
sequence_softmax = _sequence_stub("sequence_softmax")
sequence_pool = _sequence_stub("sequence_pool")
sequence_concat = _sequence_stub("sequence_concat")
sequence_first_step = _sequence_stub("sequence_first_step")
sequence_last_step = _sequence_stub("sequence_last_step")
sequence_slice = _sequence_stub("sequence_slice")
sequence_expand = _sequence_stub("sequence_expand")
sequence_expand_as = _sequence_stub("sequence_expand_as")
sequence_pad = _sequence_stub("sequence_pad")
sequence_unpad = _sequence_stub("sequence_unpad")
sequence_reshape = _sequence_stub("sequence_reshape")
sequence_scatter = _sequence_stub("sequence_scatter")
sequence_enumerate = _sequence_stub("sequence_enumerate")
sequence_reverse = _sequence_stub("sequence_reverse")


__all__ = [
    "while_loop", "cond", "case", "switch_case", "py_func",
    "fc", "embedding", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "data_norm", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "deform_conv2d", "bilinear_tensor_product",
    "prelu", "row_conv", "spectral_norm", "nce", "static_pylayer",
    "sparse_embedding", "sequence_conv", "sequence_softmax",
    "sequence_pool", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_slice", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_reverse",
]
