"""paddle.dataset.mnist (reference: dataset/mnist.py:102 train, :129
test): legacy reader creators over the modern MNIST Dataset (IDX
parser, paddle_tpu/vision/datasets.py). Pass local IDX(.gz) paths —
no network egress."""
from .common import _reader_over

__all__ = ["train", "test"]


def _make(image_path, label_path):
    from ..vision.datasets import MNIST
    if image_path is None or label_path is None:
        def raise_no_path():
            raise RuntimeError(
                "paddle.dataset.mnist: no network egress — pass local "
                "IDX(.gz) paths: mnist.train(image_path=..., "
                "label_path=...)")
        return _reader_over(raise_no_path)
    return _reader_over(lambda: MNIST(image_path=image_path,
                                      label_path=label_path))


def train(image_path=None, label_path=None):
    return _make(image_path, label_path)


def test(image_path=None, label_path=None):
    return _make(image_path, label_path)
