"""paddle.dataset.cifar (reference: dataset/cifar.py): legacy reader
creators over the modern Cifar10/Cifar100 Datasets (pickle-batch
parser). Pass the local archive path."""
from .common import _reader_over

__all__ = ["train10", "test10", "train100", "test100"]


def _make(cls_name, data_file, mode):
    from ..vision import datasets as V
    cls = getattr(V, cls_name)
    return _reader_over(lambda: cls(data_file=data_file, mode=mode))


def train10(data_file=None):
    return _make("Cifar10", data_file, "train")


def test10(data_file=None):
    return _make("Cifar10", data_file, "test")


def train100(data_file=None):
    return _make("Cifar100", data_file, "train")


def test100(data_file=None):
    return _make("Cifar100", data_file, "test")
