"""paddle.dataset.voc2012 (reference: dataset/voc2012.py:54): legacy
reader creators over the modern VOC2012 Dataset (tar layout parser)."""
from .common import _reader_over

__all__ = ["train", "test", "val"]


def _make(mode, data_file):
    from ..vision.datasets_voc_flowers import VOC2012
    return _reader_over(lambda: VOC2012(data_file=data_file, mode=mode))


def train(data_file=None):
    return _make("train", data_file)


def test(data_file=None):
    return _make("test", data_file)


def val(data_file=None):
    return _make("valid", data_file)
