"""paddle.dataset.flowers (reference: dataset/flowers.py): legacy reader
creators over the modern Flowers Dataset (102flowers tgz + .mat splits).
``mapper`` is applied per sample; ``use_xmap`` runs it on a thread pool;
``cycle`` loops forever — the reference's knobs, honored."""
from .common import _reader_over

# reference default: min(4, cpu_count) mapper workers
_XMAP_THREADS = 4

__all__ = ["train", "test", "valid"]


def _make(mode, data_file, label_file, setid_file, mapper=None,
          buffered_size=1024, use_xmap=True, cycle=False):
    from ..vision.datasets_voc_flowers import Flowers
    base = _reader_over(lambda: Flowers(
        data_file=data_file, label_file=label_file,
        setid_file=setid_file, mode=mode))
    reader = base
    if cycle:
        def reader():
            while True:
                yield from base()
    out = reader
    if mapper is not None:
        from .. import reader as R
        if use_xmap:
            out = R.xmap_readers(mapper, reader, _XMAP_THREADS,
                                 buffered_size)
        else:
            out = R.map_readers(mapper, reader)
    return out


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
          data_file=None, label_file=None, setid_file=None):
    return _make("train", data_file, label_file, setid_file, mapper,
                 buffered_size, use_xmap, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
         data_file=None, label_file=None, setid_file=None):
    return _make("test", data_file, label_file, setid_file, mapper,
                 buffered_size, use_xmap, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
          data_file=None, label_file=None, setid_file=None):
    return _make("valid", data_file, label_file, setid_file, mapper,
                 buffered_size, use_xmap, cycle)
