"""paddle.dataset.common (reference: dataset/common.py): DATA_HOME,
md5file, and the shared reader plumbing."""
from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "md5file", "dataset_path"]

DATA_HOME = os.environ.get(
    "PADDLE_DATASET_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle", "dataset"))


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def dataset_path(name, filename=None):
    """Conventional local location for a dataset file (no download —
    zero-egress environment; place archives under DATA_HOME/<name>/)."""
    p = os.path.join(DATA_HOME, name)
    return os.path.join(p, filename) if filename else p


def _reader_over(dataset_factory):
    """Wrap a Dataset-instance factory as a legacy reader creator."""

    def reader():
        ds = dataset_factory()
        for i in range(len(ds)):
            yield ds[i]

    return reader
