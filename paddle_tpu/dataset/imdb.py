"""paddle.dataset.imdb (reference: dataset/imdb.py): legacy reader
creators over the modern Imdb Dataset (aclImdb tar parser). The
caller's ``word_idx`` (from :func:`word_dict`) is the encoding
vocabulary, per the reference contract."""
from .common import _reader_over

__all__ = ["train", "test", "word_dict"]


def word_dict(data_file=None, cutoff=150):
    from ..text.datasets import Imdb
    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx


def train(word_idx=None, data_file=None):
    from ..text.datasets import Imdb
    return _reader_over(lambda: Imdb(data_file=data_file, mode="train",
                                     word_idx=word_idx))


def test(word_idx=None, data_file=None):
    from ..text.datasets import Imdb
    return _reader_over(lambda: Imdb(data_file=data_file, mode="test",
                                     word_idx=word_idx))
