"""paddle.dataset — the legacy reader-creator dataset package.

Reference: python/paddle/dataset/ (mnist.py:102 train/test,
uci_housing.py:107, imdb.py, imikolov.py, cifar.py, flowers.py,
voc2012.py, common.py). Each submodule exposes ``train()``/``test()``
reader creators (zero-arg callables yielding samples). They delegate to
this repo's modern Dataset classes (paddle_tpu.vision.datasets,
paddle_tpu.text.datasets), which parse the SAME upstream archive
formats from local paths — this environment has no network egress, so
the legacy auto-download becomes explicit path arguments (or the
``PADDLE_DATASET_HOME`` convention via ``common.DATA_HOME``).
"""
from . import common, mnist, cifar, uci_housing, imdb, imikolov  # noqa: F401
from . import flowers, voc2012  # noqa: F401

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "flowers", "voc2012"]
