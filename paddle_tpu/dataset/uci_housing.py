"""paddle.dataset.uci_housing (reference: uci_housing.py:107 train,
:133 test): legacy reader creators over the modern UCIHousing Dataset
(housing.data parser + train-split normalization)."""
from .common import _reader_over

__all__ = ["train", "test"]


def train(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_over(lambda: UCIHousing(data_file=data_file,
                                           mode="train"))


def test(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_over(lambda: UCIHousing(data_file=data_file,
                                           mode="test"))
