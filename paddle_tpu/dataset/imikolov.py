"""paddle.dataset.imikolov (reference: dataset/imikolov.py): legacy
reader creators over the modern Imikolov Dataset (PTB tar parser). The
caller's ``word_idx`` (from :func:`build_dict`) is the encoding
vocabulary, per the reference contract."""
from .common import _reader_over

__all__ = ["train", "test", "build_dict"]


def build_dict(data_file=None, min_word_freq=50):
    from ..text.datasets import Imikolov
    return Imikolov(data_file=data_file, mode="train",
                    min_word_freq=min_word_freq, data_type="SEQ").word_idx


def train(word_idx=None, n=5, data_file=None):
    from ..text.datasets import Imikolov
    return _reader_over(lambda: Imikolov(
        data_file=data_file, data_type="NGRAM", window_size=n,
        mode="train", word_idx=word_idx))


def test(word_idx=None, n=5, data_file=None):
    from ..text.datasets import Imikolov
    return _reader_over(lambda: Imikolov(
        data_file=data_file, data_type="NGRAM", window_size=n,
        mode="test", word_idx=word_idx))
