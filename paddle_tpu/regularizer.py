"""paddle.regularizer — weight-decay regularizers.

Reference: python/paddle/regularizer.py (L1Decay:51, L2Decay:169).
Optimizers consume these through ``weight_decay=``: L2Decay collapses to
the coefficient the update kernels already apply (decoupled/coupled per
optimizer, as in the reference); L1Decay adds ``coeff * sign(p)`` to the
gradient before the update (the reference appends the same sign-op to
the backward program).
"""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: loss += coeff/2 * ||w||^2, i.e. grad += coeff*w."""


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: loss += coeff * ||w||_1, i.e. grad += coeff*sign(w)."""


__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]
