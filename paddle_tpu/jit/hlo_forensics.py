"""HLO fusion forensics: measure fusion as a property, not a hope.

XLA's fusion pass is the single biggest lever between "the program the
trace describes" and "the kernels the chip launches" (the Operator
Fusion in XLA analysis, PAPERS.md): a refactor — or a JAX/XLA upgrade —
that splits a hot fused region doubles the HBM traffic of everything
that used to stay in registers, and nothing in the test suite notices
because the VALUES are identical. This module turns the compiled HLO
text (``jit.TrainStep(capture_hlo=True)``, ``LLMEngine.
ragged_step_hlo()``) into counted, gateable numbers:

- ``fusion_count`` — fusion instruction defs across the whole module
  (while/scan bodies included): a defused region shows up as MORE
  fusions (the one region becomes several) or more unfused entry ops;
- ``kernel_count`` — entry-computation instruction defs that launch
  work (everything except parameter/constant/tuple/get-tuple-element/
  bitcast): the per-step launch/thunk count proxy;
- ``fusion_bytes_total`` / ``fusion_bytes_max`` — bytes touched per
  fused region (result + operand buffers read off the instruction's
  inline shapes), summed and worst-case: a split region re-materializes
  its intermediate, so bytes-touched RISES when fusion regresses;
- ``fusion_kinds`` — kLoop/kInput/kOutput breakdown.

All of it is deterministic for a pinned jaxlib — which is exactly the
point: ``tools/proxy_bench.py`` gates these against the checked-in
baseline with direction-aware tolerances, so the upgrade that silently
costs 2x on chip fails CI in this chip-free container instead
(``--defuse`` is the injected regression proving the gate fires).
"""
from __future__ import annotations

import re

#: bytes per element for the HLO shape dtypes this stack emits
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: `f32[8,128]` / `s32[]` shape tokens (layout suffixes `{1,0}` ignored)
_SHAPE_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]")

#: one instruction definition: `%name = <shape-or-tuple> opname(`
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+ = (?:\([^)]*\)|\S+) ([\w\-]+)\(")

_FUSION_KIND_RE = re.compile(r"kind=(k\w+)")

#: entry-computation defs that launch no work — everything else is a
#: kernel/thunk proxy on the CPU/TPU thunk schedule
_FREE_OPS = ("parameter", "constant", "tuple", "get-tuple-element",
             "bitcast")


def shape_bytes(text: str) -> int:
    """Total bytes of every shape token in ``text`` (a def line's
    result type + inline operand types)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _entry_lines(hlo_text: str):
    """Instruction lines of the ENTRY computation only."""
    out, in_entry = [], False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            out.append(line)
    return out


def fusion_stats(hlo_text: str) -> dict:
    """Parse one compiled HLO module's text into the fusion-forensics
    numbers (see module docstring). Pure text analysis — no device
    work, deterministic for a pinned compiler."""
    fusion_bytes = []
    fusion_kinds: dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if m is None or m.group(1) != "fusion":
            continue
        fusion_bytes.append(shape_bytes(line.split(", calls=")[0]))
        km = _FUSION_KIND_RE.search(line)
        if km:
            fusion_kinds[km.group(1)] = fusion_kinds.get(km.group(1), 0) + 1
    kernels = 0
    instructions = 0
    for line in _entry_lines(hlo_text):
        m = _DEF_RE.match(line)
        if m is None:
            continue
        instructions += 1
        if m.group(1) not in _FREE_OPS:
            kernels += 1
    return {
        "fusion_count": len(fusion_bytes),
        "kernel_count": kernels,
        "entry_instruction_count": instructions,
        "fusion_bytes_total": sum(fusion_bytes),
        "fusion_bytes_max": max(fusion_bytes, default=0),
        "fusion_kinds": dict(sorted(fusion_kinds.items())),
    }


#: the launch-accounting marker: rsqrt appears in this stack's decode
#: bodies ONLY inside rms_norm (attention scales by a python-float
#: 1/sqrt(d), sampling/PRNG/softmax emit none), so counting rsqrt ops
#: in an UNOPTIMIZED lowering counts rms_norm sites — a fixed number
#: per decoder-layer body plus one final norm
_MARKER_RE = re.compile(r"\brsqrt\b")


def launch_stats(program_text: str, *, num_layers,
                 markers_per_body=2, overhead_markers=1,
                 tokens_per_invocation=1) -> dict:
    """Launch accounting over an UNOPTIMIZED StableHLO lowering
    (``jit(f).lower(args).as_text()``): how many times does the decoder
    layer body appear as a distinct site in the program?

    The measurable is structural, not a fusion heuristic: an unrolled
    layer loop inlines the body ``num_layers`` times; a ``lax.scan``
    over stacked weights emits ONE body inside ``stablehlo.while``.
    Each body carries ``markers_per_body`` rms_norm (rsqrt) markers and
    the program carries ``overhead_markers`` non-layer markers (the
    final norm), so

        layer_body_sites = (markers - overhead) / markers_per_body
        launches_per_token = layer_body_sites / tokens_per_invocation

    ``tokens_per_invocation`` > 1 accounts a burst executable, whose
    one invocation's while_loop covers that many tokens per row —
    model-scope burst decode reaches 1/burst_tokens launches per token.
    ``collapsed`` is the gateable headline: True iff the layer loop
    lives inside the program (<= 1 body site). Raises ValueError when
    the marker count is inconsistent with the constants (e.g. a body
    gained a norm without the caller re-deriving markers_per_body) —
    silently mis-dividing would fabricate a launch count.
    """
    markers = len(_MARKER_RE.findall(program_text))
    sites_num = markers - int(overhead_markers)
    if sites_num < 0 or sites_num % int(markers_per_body):
        raise ValueError(
            f"launch_stats: {markers} rsqrt markers do not decompose as "
            f"{overhead_markers} overhead + N x {markers_per_body} "
            f"per-body markers — the traced body changed; re-derive the "
            f"marker constants")
    sites = sites_num // int(markers_per_body)
    return {
        "marker_count": markers,
        "layer_body_sites": sites,
        "num_layers": int(num_layers),
        "launches_per_token": sites / float(tokens_per_invocation),
        "collapsed": sites <= 1,
    }


def mixed_launch_stats(program_text: str, *, num_layers,
                       kinds, overhead_markers=1,
                       tokens_per_invocation=1,
                       exclusive=False) -> dict:
    """Launch accounting for a MIXED invocation — one program whose
    body contains more than one kind of decoder-layer body (the
    serving ragged step runs prefill-chunk rows and decode rows in the
    same fixed-shape executable).

    ``kinds`` maps a body-kind name to its markers-per-body count, e.g.
    ``{"prefill": 2, "decode": 2}``. Each kind's site count is
    structural — ``0`` (absent), ``1`` (scan-collapsed) or
    ``num_layers`` (unrolled) — so the total marker count must
    decompose as

        markers = overhead + sum_k sites_k * markers_per_body_k

    with every ``sites_k`` in ``{0, 1, num_layers}`` (``{1,
    num_layers}`` when ``exclusive=True``, which asserts every kind is
    present — the mixed step always carries both bodies). The
    decomposition must be UNIQUE: zero solutions means the traced body
    changed under the caller's constants, several means the marker
    algebra cannot attribute sites to kinds — both raise ValueError
    rather than fabricate a launch count.
    """
    import itertools

    markers = len(_MARKER_RE.findall(program_text))
    budget = markers - int(overhead_markers)
    names = sorted(kinds)
    L = int(num_layers)
    cand = (1, L) if exclusive else (0, 1, L)
    solutions = []
    for combo in itertools.product(cand, repeat=len(names)):
        if sum(s * int(kinds[n]) for s, n in zip(combo, names)) == budget:
            if combo not in solutions:
                solutions.append(combo)
    if len(solutions) != 1:
        why = "no assignment matches" if not solutions else \
            f"{len(solutions)} assignments match"
        raise ValueError(
            f"mixed_launch_stats: {markers} rsqrt markers do not "
            f"decompose as {overhead_markers} overhead + per-kind body "
            f"sites in {cand} for kinds {dict(kinds)} ({why}) — the "
            f"traced body changed; re-derive the marker constants")
    sites = dict(zip(names, solutions[0]))
    total = sum(sites.values())
    return {
        "marker_count": markers,
        "sites": sites,
        "total_body_sites": total,
        "num_layers": L,
        "launches_per_token": total / float(tokens_per_invocation),
        "collapsed": all(s <= 1 for s in sites.values()),
    }


__all__ = ["fusion_stats", "launch_stats", "mixed_launch_stats",
           "shape_bytes"]
