"""jit.save / jit.load — deployable compiled artifacts.

TPU-native analog of the reference's saved-inference format
(reference: python/paddle/jit/api.py jit.save -> TranslatedLayer via
jit/translated_layer.py; C++ executable container paddle/fluid/jit/
layer.h). The program format is **serialized StableHLO** via
``jax.export`` — the portable XLA artifact (the role ProgramDesc/PIR
serialization plays in the reference) — beside the params saved with
``paddle_tpu.save``:

    path.pdmodel    serialized StableHLO (versioned, forward-compatible)
    path.pdiparams  parameter state_dict
    path.meta.json  input/output tree metadata

``load`` returns a TranslatedLayer: callable, parameters() exposed, usable
for inference or as a frozen sub-layer.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core.tensor import Tensor
from ..core.dtype import to_jax_dtype


class InputSpec:
    """Shape/dtype spec (reference: python/paddle/static/input_spec.py).
    Use None for dynamic dims — exported as symbolic dimensions."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def to_sds(self, sym_scope=None):
        if any(s is None or (isinstance(s, int) and s < 0) for s in self.shape):
            dims = ",".join(
                (chr(ord("a") + i) if (s is None or s < 0) else str(s))
                for i, s in enumerate(self.shape))
            shape = jax_export.symbolic_shape(dims, scope=sym_scope)
        else:
            shape = tuple(self.shape)
        return jax.ShapeDtypeStruct(shape, to_jax_dtype(self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _pure_forward(layer):
    """layer forward as (params_dict, *arrays) -> arrays pytree."""
    from ..core import autograd as _ag

    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    state = {**{f"p:{k}": v for k, v in params.items()},
             **{f"b:{k}": v for k, v in buffers.items()}}

    def pure(state_arrays, *arrays):
        saved = {k: t._data for k, t in state.items()}
        try:
            for k, t in state.items():
                t._data = state_arrays[k]
            with _ag.no_grad():
                out = layer(*[Tensor(a) for a in arrays])
        finally:
            for k, t in state.items():
                t._data = saved[k]
        return jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t,
                            out, is_leaf=lambda x: isinstance(x, Tensor))

    return pure, state


def save(layer, path, input_spec=None, **config):
    """Export ``layer`` at ``path`` (reference: jit.save api.py).

    input_spec: list of InputSpec/Tensor examples. Required unless the
    layer was called through to_static and has a cached signature.
    """
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        if input_spec is None:
            raise ValueError("jit.save requires input_spec")
        # one shared symbolic scope: jax.export rejects mixing symbolic
        # dimensions created in different scopes, so every dynamic-dim
        # InputSpec must resolve its symbols against the same scope
        sym_scope = jax_export.SymbolicScope()
        specs = []
        for s in input_spec:
            if isinstance(s, InputSpec):
                specs.append(s.to_sds(sym_scope))
            elif isinstance(s, Tensor):
                specs.append(jax.ShapeDtypeStruct(tuple(s.shape),
                                                  s._data.dtype))
            else:
                a = jnp.asarray(s)
                specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

        pure, state = _pure_forward(layer)
        state_arrays = {k: t._data for k, t in state.items()}
        state_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                       for k, v in state_arrays.items()}
        exp = jax_export.export(jax.jit(pure))(state_specs, *specs)

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exp.serialize())
        from ..framework import save as fsave
        fsave({k: Tensor(v) for k, v in state_arrays.items()},
              path + ".pdiparams")
        with open(path + ".meta.json", "w") as f:
            json.dump({
                "format": "paddle_tpu.stablehlo.v1",
                # stable artifact version header (round-3 verdict item 10):
                # loaders reject artifacts from an incompatible major
                "artifact_version": ARTIFACT_VERSION,
                "inputs": [{"shape": [None if not isinstance(x, int) else x
                                      for x in s.shape],
                            "dtype": str(s.dtype)} for s in specs],
                "n_inputs": len(specs),
            }, f)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()
    return path


class TranslatedLayer:
    """Loaded artifact (reference: jit/translated_layer.py TranslatedLayer)."""

    def __init__(self, exported, state_arrays, meta):
        self._exported = exported
        self._state = state_arrays
        self._meta = meta
        self.training = False

    def __call__(self, *args):
        arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(self._state, *arrays)
        return jax.tree.map(lambda a: Tensor(a), out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a loaded inference artifact cannot be trained; "
                           "load the raw params with paddle_tpu.load instead")

    def parameters(self):
        return [Tensor(v) for k, v in self._state.items()
                if k.startswith("p:")]

    def state_dict(self):
        return {k.split(":", 1)[1]: Tensor(v) for k, v in self._state.items()}

    @property
    def input_metas(self):
        return self._meta.get("inputs", [])


# Artifact versioning: MAJOR.MINOR. MAJOR bumps on breaking layout
# changes (loader refuses); MINOR on additive metadata (loader accepts).
ARTIFACT_VERSION = [1, 1]


def load(path):
    """Load a jit.save artifact (reference: jit.load api.py)."""
    meta = {}
    meta_path = path + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        ver = meta.get("artifact_version")
        if ver is not None and int(ver[0]) != ARTIFACT_VERSION[0]:
            raise ValueError(
                f"artifact {path!r} has version {ver} but this runtime "
                f"reads major version {ARTIFACT_VERSION[0]}; re-export "
                "with this version's jit.save")
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    from ..framework import load as fload
    state = fload(path + ".pdiparams")
    state_arrays = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                    for k, v in state.items()}
    return TranslatedLayer(exported, state_arrays, meta)


__all__ = ["save", "load", "InputSpec", "TranslatedLayer"]
