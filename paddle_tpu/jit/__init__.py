"""paddle_tpu.jit — the compiled execution path.

Analog of the reference's jit stack (python/paddle/jit/api.py:197 to_static;
SOT bytecode capture jit/sot/; CINN compilation). On this stack the whole
pipeline collapses: the eager engine already executes jnp ops on ``._data``
arrays, so *tracing the eager code itself* under ``jax.jit`` captures
forward, tape-backward, optimizer update, buffer mutations, and RNG into a
single XLA computation — the role the reference needs SOT + PIR + CINN for.

- ``to_static(layer_or_fn)``: compiled forward with buffer-mutation capture
  and per-(shapes, training-flag) executable cache (the reference's program
  cache, paddle/fluid/framework/op_registry + executable cache).
- ``TrainStep(model, loss_fn, optimizer)``: one fused step — forward + loss +
  backward + optimizer — jit-compiled, params/optimizer state donated.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import autograd as _ag
from ..core import random as _rng
from ..core.tensor import Tensor


def _collect_state(layer):
    """All tensors whose values a Layer's forward may read or write."""
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    return params, buffers


class _Installed:
    """Temporarily swap Tensor._data for traced arrays, restore on exit."""

    def __init__(self, tensors: dict):
        self.tensors = tensors

    def __enter__(self):
        self.saved = {k: t._data for k, t in self.tensors.items()}
        return self

    def install(self, arrays: dict):
        for k, t in self.tensors.items():
            t._data = arrays[k]

    def current(self):
        return {k: t._data for k, t in self.tensors.items()}

    def __exit__(self, *exc):
        for k, t in self.tensors.items():
            t._data = self.saved[k]
        return False


def _tree_to_arrays(tree):
    return jax.tree.map(lambda x: x._data if isinstance(x, Tensor) else x, tree,
                        is_leaf=lambda x: isinstance(x, Tensor))


def _tree_to_tensors(tree):
    return jax.tree.map(
        lambda x: Tensor(x) if isinstance(x, (jax.Array,)) else x, tree)


class StaticFunction:
    """Compiled forward wrapper (reference: StaticFunction in
    python/paddle/jit/dy2static/program_translator.py).

    Guard/fallback semantics (the SOT graph-break analog, reference
    jit/sot/translate.py): the cache key guards on every input's
    shape+dtype and every non-tensor argument's value, so a changed Python
    argument or shape re-traces rather than reusing a stale program. When
    the traced function turns out to need concrete tensor VALUES for Python
    control flow (a data-dependent ``if``/``while``), tracing raises — the
    wrapper then graph-breaks: it marks the signature and permanently runs
    it eagerly (one warning), instead of silently baking a single branch.
    """

    def __init__(self, fn, layer=None):
        # SOT loop capture (round-5): safe tensor-dependent `while` loops
        # are source-rewritten to compile ONCE via lax.while_loop instead
        # of one specialization per trip count (loop_rewrite.py)
        from .loop_rewrite import rewrite_loops
        fn = rewrite_loops(fn)
        self._fn = fn
        self._layer = layer
        self._cache = {}
        self._graph_broken = set()
        # SOT-lite value guards (core/branch_guards.py): per signature, a
        # dict of branch-decision-vector -> compiled specialization
        self._guarded = {}
        functools.update_wrapper(self, fn)

    def _key(self, flat_args):
        sig = tuple(
            (a.shape, str(a.dtype)) if hasattr(a, "shape") else ("py", repr(a))
            for a in flat_args)
        training = self._layer.training if self._layer is not None else None
        return (sig, training)

    def __call__(self, *args, **kwargs):
        layer = self._layer
        params, buffers = _collect_state(layer) if layer is not None else ({}, {})
        state = {**{f"p:{k}": v for k, v in params.items()},
                 **{f"b:{k}": v for k, v in buffers.items()}}
        flat_in, in_tree = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arr_in = [x._data if isinstance(x, Tensor) else x for x in flat_in]
        tensor_pos = [i for i, x in enumerate(flat_in) if isinstance(x, Tensor)]
        key = self._key(arr_in)
        if key in self._graph_broken:
            return self._fn(*args, **kwargs)
        if key in self._guarded:
            return self._run_guarded(key, state, flat_in, in_tree,
                                     tensor_pos, arr_in, args, kwargs)

        if key not in self._cache:
            self._cache[key] = self._build_pure(state, flat_in, in_tree,
                                                tensor_pos)
            self._maybe_dump_ir(key, state, arr_in, tensor_pos)

        state_arrays = {k: t._data for k, t in state.items()}
        dyn = [arr_in[i] for i in tensor_pos]
        try:
            out_arrays, new_state = self._cache[key](
                state_arrays, _rng.next_key(), *dyn)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError):
            del self._cache[key]
            # SOT-lite: tensor-dependent `if` — record the branch-decision
            # vector eagerly, then compile a per-branch specialization with
            # value guards (reference capability: jit/sot re-traces per
            # guarded branch, translate.py:106). Non-bool concretizations
            # (int shapes etc.) still graph-break.
            from ..core import branch_guards as _bg
            with _bg.record() as rec:
                out = self._fn(*args, **kwargs)
            decisions = rec.decisions
            if not decisions:
                import warnings
                warnings.warn(
                    f"jit.to_static({getattr(self._fn, '__name__', self._fn)}): "
                    "tensor-dependent Python control flow cannot be "
                    "captured — falling back to eager for this input "
                    "signature (use paddle.where or static shapes)",
                    stacklevel=2)
                self._graph_broken.add(key)
                return out
            self._warn_loop_sites(rec.loop_sites)
            from collections import OrderedDict
            entry = {"specs": OrderedDict(), "last": decisions}
            entry["specs"][decisions] = self._build_pure(
                state, flat_in, in_tree, tensor_pos, decisions)
            self._guarded[key] = entry
            return out    # eager result this call; compiled from the next
        # commit buffer mutations (running stats etc.); params are read-only here
        for k, t in state.items():
            if k.startswith("b:"):
                t._data = new_state[k]
        return _tree_to_tensors(out_arrays)

    def _build_pure(self, state, flat_in, in_tree, tensor_pos,
                    decisions=None):
        """jit the functionalized eager call. With ``decisions``, the trace
        replays that branch-decision vector at every tensor bool and the
        condition values ride along as guard outputs."""
        from ..core import branch_guards as _bg

        installer = _Installed(state)
        # template keeps only non-tensor leaves; tensor slots are filled
        # from dyn_args each call (so no input batch is pinned in HBM)
        template = [None if isinstance(x, Tensor) else x for x in flat_in]

        def pure(state_arrays, rng_key, *dyn_args):
            with installer:
                installer.install(state_arrays)
                with _rng.capture_rng(rng_key), _ag.no_grad():
                    vals = list(template)
                    for i, a in zip(tensor_pos, dyn_args):
                        vals[i] = a
                    a_args, a_kwargs = jax.tree.unflatten(in_tree, [
                        Tensor(v) if i in tensor_pos else v
                        for i, v in enumerate(vals)])
                    if decisions is None:
                        out = self._fn(*a_args, **a_kwargs)
                        conds = None
                    else:
                        with _bg.replay(decisions) as rp:
                            out = self._fn(*a_args, **a_kwargs)
                        conds = tuple(
                            jnp.reshape(jnp.asarray(c), ()).astype(bool)
                            for c in rp.conds)
                new_state = installer.current()
            out_arrays = jax.tree.map(
                lambda x: x._data if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            if decisions is None:
                return out_arrays, new_state
            return out_arrays, new_state, conds

        return jax.jit(pure)

    def _run_guarded(self, key, state, flat_in, in_tree, tensor_pos,
                     arr_in, args, kwargs):
        """Dispatch among branch specializations.

        Run the last-used specialization; its guard outputs are the
        condition values computed on the CURRENT inputs, so the first
        guard that disagrees with the specialization's decision vector
        reveals the true branch — dispatch to (or record+compile) the
        right specialization instead of permanent eager fallback.
        """
        from ..core import branch_guards as _bg

        entry = self._guarded[key]
        state_arrays = {k: t._data for k, t in state.items()}
        dyn = [arr_in[i] for i in tensor_pos]
        vec = entry["last"]
        tried = set()
        for _ in range(len(entry["specs"]) + 1):
            tried.add(vec)
            try:
                out_arrays, new_state, conds = entry["specs"][vec](
                    state_arrays, _rng.next_key(), *dyn)
            except _bg.GuardOverflow:
                # the branch STRUCTURE is input-dependent beyond value
                # specialization — drop the spec and re-record
                del entry["specs"][vec]
                break
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError):
                # a NON-bool concretization inside a guarded branch: value
                # guards cannot capture it — graph-break like the plain
                # path (the removed-fallback regression)
                import warnings
                warnings.warn(
                    f"jit.to_static({getattr(self._fn, '__name__', self._fn)}): "
                    "tensor-dependent Python control flow cannot be "
                    "captured — falling back to eager for this input "
                    "signature (use paddle.where or static shapes)",
                    stacklevel=2)
                self._graph_broken.add(key)
                del self._guarded[key]
                return self._fn(*args, **kwargs)
            observed = tuple(bool(c) for c in conds)
            if observed == vec:
                entry["last"] = vec
                if hasattr(entry["specs"], "move_to_end"):
                    entry["specs"].move_to_end(vec)   # LRU recency
                for k, t in state.items():
                    if k.startswith("b:"):
                        t._data = new_state[k]
                return _tree_to_tensors(out_arrays)
            # first divergent guard is computed on the shared prefix path,
            # so its value is the true decision
            k_div = next((i for i, (o, v) in enumerate(zip(observed, vec))
                          if o != v), None)
            if k_div is None:
                break    # lengths diverged: structure mismatch, re-record
            prefix = vec[:k_div] + (observed[k_div],)
            matches = [v for v in entry["specs"]
                       if v[:k_div + 1] == prefix and v not in tried]
            if matches:
                vec = matches[0]   # refine along any consistent candidate
                continue
            break
        # unknown branch path: eager run records it; compile for next time
        with _bg.record() as rec:
            out = self._fn(*args, **kwargs)
        decisions = rec.decisions
        if decisions and decisions not in entry["specs"]:
            self._warn_loop_sites(rec.loop_sites)
            entry["specs"][decisions] = self._build_pure(
                state, flat_in, in_tree, tensor_pos, decisions)
            # bounded specialization cache with LRU eviction (round-3
            # verdict item 5: k independent branches can demand 2^k specs;
            # a data-dependent Python loop demands one per trip count)
            from ..core.flags import GLOBAL_FLAGS
            bound = max(int(GLOBAL_FLAGS.get(
                "sot_specialization_cache_size")), 1)
            while len(entry["specs"]) > bound:
                entry["specs"].popitem(last=False)
        if decisions:
            entry["last"] = decisions
        return out

    def _maybe_dump_ir(self, key, state, arr_in, tensor_pos):
        """FLAGS_logging_pir_py_code_dir: dump the jaxpr text of each
        newly-compiled specialization (the reference's PIR py-code dump,
        logging_utils; jaxpr/StableHLO is the IR on this stack)."""
        from ..core.flags import GLOBAL_FLAGS
        out_dir = GLOBAL_FLAGS.get("logging_pir_py_code_dir")
        if not out_dir:
            return
        try:
            import os
            os.makedirs(out_dir, exist_ok=True)
            state_arrays = {k: t._data for k, t in state.items()}
            dyn = [arr_in[i] for i in tensor_pos]
            # constant key: a debug dump must not advance the global RNG
            # stream (that would change model numerics when the flag is on)
            dump_key = jax.random.PRNGKey(0)
            jaxpr = jax.make_jaxpr(self._cache[key]._fun
                                   if hasattr(self._cache[key], "_fun")
                                   else self._cache[key])(
                state_arrays, dump_key, *dyn)
            name = getattr(self._fn, "__name__", "fn")
            path = os.path.join(
                out_dir, f"{name}_{abs(hash(key)) & 0xFFFFFFFF:08x}.jaxpr")
            # jaxpr text renders constants as names only; append a consts
            # section so the dump is self-contained, with
            # FLAGS_logging_pir_py_code_int_tensor_element_limit bounding
            # how many elements each constant renders.
            # FLAGS_logging_trunc_pir_py_code caps the dump file itself.
            import numpy as _np
            limit = int(GLOBAL_FLAGS.get(
                "logging_pir_py_code_int_tensor_element_limit"))
            text = str(jaxpr)
            if getattr(jaxpr, "consts", None):
                lines = ["", "consts:"]
                for i, c in enumerate(jaxpr.consts):
                    a = _np.asarray(c)
                    body = _np.array2string(
                        a, threshold=max(limit, 1),
                        edgeitems=max(limit // 2, 1))
                    lines.append(f"  c{i}: {a.dtype}{list(a.shape)} = {body}")
                text += "\n".join(lines) + "\n"
            if GLOBAL_FLAGS.get("logging_trunc_pir_py_code") \
                    and len(text) > 65536:
                text = text[:65536] + "\n... [truncated by " \
                    "FLAGS_logging_trunc_pir_py_code]\n"
            with open(path, "w") as f:
                f.write(text)
        except Exception:
            pass  # a debug dump must never break the compile path

    def _warn_loop_sites(self, loop_sites):
        """One-time hint when a capture shows a tensor-dependent LOOP:
        value guards compile one specialization per trip count; the O(1)
        compile path is paddle.static.nn.while_loop (lax.while_loop)."""
        if not loop_sites:
            return
        warned = getattr(self, "_loop_warned", set())
        self._loop_warned = warned
        for site, n in loop_sites.items():
            if site in warned or n < 4:
                continue
            warned.add(site)
            from ..core.vlog import vlog
            vlog(0, f"to_static: tensor-dependent loop at {site[0]}:"
                    f"{site[1]} ({n} iterations) compiles one "
                    "specialization per trip count; rewrite with "
                    "paddle.static.nn.while_loop to compile once",
                 component="jit")

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """``paddle.jit.to_static`` analog (reference: python/paddle/jit/api.py:197)."""

    def deco(fn):
        from ..nn import Layer
        if isinstance(fn, Layer):
            layer = fn
            static = StaticFunction(layer.forward, layer)
            layer.forward = static
            return layer
        return StaticFunction(fn, None)

    if function is None:
        return deco
    return deco(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """Fused compiled training step.

    Traces the *eager* engine — forward, tape backward, optimizer — into one
    XLA executable. Parameter and optimizer-state buffers are donated so
    updates are in-place in HBM (the reference needs fused multi-tensor
    kernels + interpreter scheduling for the same effect, SURVEY.md §3.3).

    Usage::
        step = TrainStep(model, lambda x, y: F.cross_entropy(model(x), y), opt)
        loss = step(x_batch, y_batch)
    """

    def __init__(self, model, loss_fn, optimizer):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._cache = {}
        # materialize optimizer state now so it traces as inputs
        params = [p for p in optimizer._parameter_list if not p.stop_gradient]
        self._params = {f"p{i}": p for i, p in enumerate(params)}

    def _fused_eng(self):
        eng = getattr(self.optimizer, "_fused_engine", None)
        return eng if (eng is not None and eng.active) else None

    def _opt_state_arrays(self):
        eng = self._fused_eng()
        if eng is not None:
            # fused path: optimizer state IS the engine's flat per-bucket
            # buffers — O(#dtype buckets) donated inputs, not O(n_params)
            return eng.state_arrays()
        out = {}
        for i, p in self._params.items():
            st = self.optimizer._state.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{i}.{k}"] = v
        return out

    def _install_opt_state(self, arrays):
        eng = self._fused_eng()
        if eng is not None:
            eng.install_state(arrays)
            return
        for i, p in self._params.items():
            st = {}
            prefix = f"{i}."
            for k, v in arrays.items():
                if k.startswith(prefix):
                    st[k[len(prefix):]] = v
            if st:
                self.optimizer._state[id(p)] = st

    def __call__(self, *batch):
        from ..core.flags import GLOBAL_FLAGS
        from ..io.prefetch import PIPELINE_METRICS
        _, buffers = _collect_state(self.model)
        for b in batch:
            if isinstance(b, Tensor) and getattr(b, "_donated", False):
                raise RuntimeError(
                    "TrainStep received a batch tensor whose buffer was "
                    "already donated to a previous compiled step. Staged "
                    "batches (DataLoader(use_buffer_reader=True)) are "
                    "single-use on TPU; to reuse a batch across steps, "
                    "pass your own tensor or set use_buffer_reader=False.")
        batch_arrays = tuple(b._data if isinstance(b, Tensor) else jnp.asarray(b)
                             for b in batch)
        check_finite = bool(GLOBAL_FLAGS.get("check_nan_inf"))
        # Staged-batch donation: batches the prefetch pipeline put on the
        # device (io/prefetch.py marks them _staged_h2d) are consumed
        # exactly once, so their buffers can be given back to XLA — the
        # step reuses the HBM instead of allocating fresh activations next
        # to a dead input copy. A caller-owned tensor (e.g. the bench
        # reusing one batch) is never donated.
        donate_batch = bool(batch) and jax.default_backend() != "cpu" and \
            all(isinstance(b, Tensor) and getattr(b, "_staged_h2d", False)
                for b in batch)
        key = tuple((a.shape, str(a.dtype)) for a in batch_arrays) \
            + (check_finite, donate_batch)

        if key not in self._cache:
            # Ensure optimizer state exists with final shapes: run one throwaway
            # state init by touching _param_state via a zero-grad apply is
            # avoided; instead let the traced call create state lazily inside
            # the trace — it becomes constants. To keep state as *inputs*, we
            # pre-create it here by calling the state initializer explicitly.
            self._prime_state()
            param_t = dict(self._params)
            buffer_t = {f"b:{k}": v for k, v in buffers.items()}
            opt = self.optimizer
            model = self.model
            loss_fn = self.loss_fn
            step_holder = {}

            def pure_step(param_arrays, opt_arrays, buffer_arrays, step_i, lr, rng, *b_arrays):
                inst_p = _Installed(param_t)
                inst_b = _Installed(buffer_t)
                saved_state = {pid: dict(st) for pid, st in opt._state.items()}
                eng = getattr(opt, "_fused_engine", None)
                saved_eng = eng.snapshot() if eng is not None and eng.active \
                    else None
                saved_step, saved_lr = opt._step_count, opt._lr
                saved_grads = {k: p.grad for k, p in param_t.items()}
                try:
                    with inst_p, inst_b, _rng.capture_rng(rng):
                        inst_p.install(param_arrays)
                        inst_b.install(buffer_arrays)
                        self._install_opt_state(opt_arrays)
                        opt._step_count = step_i
                        opt._lr = lr
                        for p in param_t.values():
                            p.grad = None
                        batch_tensors = [Tensor(a) for a in b_arrays]
                        loss = loss_fn(*batch_tensors)
                        loss.backward()
                        opt.step()
                        new_params = inst_p.current()
                        new_buffers = inst_b.current()
                        new_opt = self._opt_state_arrays()
                        if check_finite:
                            # compiled-path numerical sanitizer (reference:
                            # new_executor/nan_inf_utils.h under
                            # FLAGS_check_nan_inf): one fused all-finite
                            # reduction over loss + updated params, checked
                            # host-side — no per-op sync like the eager sweep
                            import jax.numpy as _jnp
                            finite = _jnp.isfinite(loss._data).all()
                            for v in new_params.values():
                                if _jnp.issubdtype(v.dtype, _jnp.inexact):
                                    finite &= _jnp.isfinite(v).all()
                            return new_params, new_opt, new_buffers, \
                                loss._data, finite
                        return new_params, new_opt, new_buffers, loss._data
                finally:
                    opt._state = saved_state
                    if saved_eng is not None:
                        eng.restore(saved_eng)
                    opt._step_count, opt._lr = saved_step, saved_lr
                    for k, p in param_t.items():
                        p.grad = saved_grads[k]

            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            if donate_batch:
                # b_arrays start after the 6 fixed args of pure_step
                donate = donate + tuple(range(6, 6 + len(batch_arrays)))
            self._cache[key] = jax.jit(pure_step, donate_argnums=donate)

        param_arrays = {k: p._data for k, p in self._params.items()}
        opt_arrays = self._opt_state_arrays()
        buffer_arrays = {f"b:{k}": v._data for k, v in buffers.items()}
        lr = self.optimizer.get_lr()
        step_in = self.optimizer._step_count  # inside-trace step() adds 1
        rng_key = _rng.next_key()
        eager_loss = None
        if GLOBAL_FLAGS.get("enable_cinn_accuracy_check") \
                and key not in getattr(self, "_accuracy_checked", set()):
            # FLAGS_enable_cinn_accuracy_check (reference flags.cc): once
            # per compiled specialization, recompute the loss through the
            # EAGER engine on the same params + rng key and compare within
            # the accuracy_check_* tolerances — catches a compiled-path
            # lowering that silently diverges from eager. Runs BEFORE the
            # compiled call: on TPU the compiled step donates the param /
            # opt-state buffers, so reading them afterwards would hit
            # deleted arrays. Buffer bindings mutated by the eager forward
            # (e.g. running stats) are restored — the compiled step's
            # updates are the ones that count.
            self._accuracy_checked = getattr(self, "_accuracy_checked", set())
            self._accuracy_checked.add(key)
            saved_buf = {k: t._data for k, t in buffers.items()}
            try:
                with _rng.capture_rng(rng_key):
                    eager_loss = float(self.loss_fn(*batch).numpy())
            finally:
                for k, t in buffers.items():
                    t._data = saved_buf[k]
        PIPELINE_METRICS.record_dispatch()
        out = self._cache[key](
            param_arrays, opt_arrays, buffer_arrays,
            jnp.asarray(step_in, jnp.int32),
            jnp.asarray(lr, jnp.float32), rng_key, *batch_arrays)
        if donate_batch:
            for b in batch:
                # buffer handed to XLA: mark so a reuse raises our error
                # above instead of jax's opaque "Array has been deleted"
                b._staged_h2d = False
                b._donated = True
        if check_finite:
            new_p, new_o, new_b, loss, finite = out
            if not bool(finite):
                raise FloatingPointError(
                    f"NaN/Inf detected in compiled train step "
                    f"{self.optimizer._step_count} (FLAGS_check_nan_inf)")
        else:
            new_p, new_o, new_b, loss = out
        if eager_loss is not None:
            compiled_loss = float(jnp.asarray(loss))
            # no `or`-defaults: an explicit 0 tolerance must stay 0
            rtol = float(GLOBAL_FLAGS.get("accuracy_check_rtol_fp32"))
            atol = float(GLOBAL_FLAGS.get("accuracy_check_atol_fp32"))
            self.last_accuracy_check = {
                "eager": eager_loss, "compiled": compiled_loss}
            if abs(eager_loss - compiled_loss) > atol + rtol * abs(eager_loss):
                raise FloatingPointError(
                    f"compiled/eager loss mismatch (FLAGS_enable_cinn_"
                    f"accuracy_check): eager {eager_loss} vs compiled "
                    f"{compiled_loss} (rtol {rtol}, atol {atol})")
        self.optimizer._step_count += 1
        for k, p in self._params.items():
            p._data = new_p[k]
        self._install_opt_state(new_o)
        for k, t in buffers.items():
            t._data = new_b[f"b:{k}"]
        return Tensor(loss)

    def _prime_state(self):
        """Create optimizer state ahead of tracing so state rides as
        donated inputs rather than baked constants. Fused optimizers build
        their dtype buckets instead (flat state, O(#buckets) inputs); the
        per-param schema priming is the fallback."""
        params = list(self._params.values())
        if self.optimizer._prime_fused(params):
            return
        for p in params:
            self.optimizer._param_state(p)


def save(layer, path, input_spec=None, **config):
    """``paddle.jit.save`` analog: persist weights + (when exportable) the
    serialized compiled program via jax.export
    (reference: python/paddle/jit/api.py save → TranslatedLayer artifacts)."""
    from ..framework.io import save as fsave
    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    fsave({"state_dict": state, "format": "paddle_tpu.jit.v1"}, path + ".pdparams")


def load(path, **config):
    from ..framework.io import load as fload
    return fload(path + ".pdparams")


def enable_to_static(flag=True):
    pass


def ignore_module(modules):
    pass

from .save_load import save, load, InputSpec, TranslatedLayer  # noqa: F401,E402


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static transcription verbosity (reference: jit/api.py
    set_verbosity -> TranslatorLogger): maps onto FLAGS_v so the vlog
    tier carries SOT diagnostics."""
    from ..core.flags import GLOBAL_FLAGS
    GLOBAL_FLAGS.set("v", int(level))


def set_code_level(level=100, also_to_stdout=False):
    """Dump transformed code up to ``level`` (reference: jit/api.py
    set_code_level). The SOT-lite pipeline has one transform stage, so any
    level >= 1 turns on specialization-dump logging via
    FLAGS_logging_pir_py_code_dir default '.' when unset."""
    from ..core.flags import GLOBAL_FLAGS
    if int(level) >= 1 and not GLOBAL_FLAGS.get("logging_pir_py_code_dir"):
        GLOBAL_FLAGS.set("logging_pir_py_code_dir", ".")
