"""paddle_tpu.jit — the compiled execution path.

Analog of the reference's jit stack (python/paddle/jit/api.py:197 to_static;
SOT bytecode capture jit/sot/; CINN compilation). On this stack the whole
pipeline collapses: the eager engine already executes jnp ops on ``._data``
arrays, so *tracing the eager code itself* under ``jax.jit`` captures
forward, tape-backward, optimizer update, buffer mutations, and RNG into a
single XLA computation — the role the reference needs SOT + PIR + CINN for.

- ``to_static(layer_or_fn)``: compiled forward with buffer-mutation capture
  and per-(shapes, training-flag) executable cache (the reference's program
  cache, paddle/fluid/framework/op_registry + executable cache).
- ``TrainStep(model, loss_fn, optimizer)``: one fused step — forward + loss +
  backward + optimizer — jit-compiled, params/optimizer state donated.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import autograd as _ag
from ..core import random as _rng
from ..core.tensor import Tensor


def _collect_state(layer):
    """All tensors whose values a Layer's forward may read or write."""
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    return params, buffers


class _Installed:
    """Temporarily swap Tensor._data for traced arrays, restore on exit."""

    def __init__(self, tensors: dict):
        self.tensors = tensors

    def __enter__(self):
        self.saved = {k: t._data for k, t in self.tensors.items()}
        return self

    def install(self, arrays: dict):
        for k, t in self.tensors.items():
            t._data = arrays[k]

    def current(self):
        return {k: t._data for k, t in self.tensors.items()}

    def __exit__(self, *exc):
        for k, t in self.tensors.items():
            t._data = self.saved[k]
        return False


def _tree_to_arrays(tree):
    return jax.tree.map(lambda x: x._data if isinstance(x, Tensor) else x, tree,
                        is_leaf=lambda x: isinstance(x, Tensor))


def _tree_to_tensors(tree):
    return jax.tree.map(
        lambda x: Tensor(x) if isinstance(x, (jax.Array,)) else x, tree)


class StaticFunction:
    """Compiled forward wrapper (reference: StaticFunction in
    python/paddle/jit/dy2static/program_translator.py).

    Guard/fallback semantics (the SOT graph-break analog, reference
    jit/sot/translate.py): the cache key guards on every input's
    shape+dtype and every non-tensor argument's value, so a changed Python
    argument or shape re-traces rather than reusing a stale program. When
    the traced function turns out to need concrete tensor VALUES for Python
    control flow (a data-dependent ``if``/``while``), tracing raises — the
    wrapper then graph-breaks: it marks the signature and permanently runs
    it eagerly (one warning), instead of silently baking a single branch.
    """

    def __init__(self, fn, layer=None):
        # SOT loop capture (round-5): safe tensor-dependent `while` loops
        # are source-rewritten to compile ONCE via lax.while_loop instead
        # of one specialization per trip count (loop_rewrite.py)
        from .loop_rewrite import rewrite_loops
        fn = rewrite_loops(fn)
        self._fn = fn
        self._layer = layer
        self._cache = {}
        self._graph_broken = set()
        # SOT-lite value guards (core/branch_guards.py): per signature, a
        # dict of branch-decision-vector -> compiled specialization
        self._guarded = {}
        functools.update_wrapper(self, fn)

    def _key(self, flat_args):
        sig = tuple(
            (a.shape, str(a.dtype)) if hasattr(a, "shape") else ("py", repr(a))
            for a in flat_args)
        training = self._layer.training if self._layer is not None else None
        return (sig, training)

    def __call__(self, *args, **kwargs):
        layer = self._layer
        params, buffers = _collect_state(layer) if layer is not None else ({}, {})
        state = {**{f"p:{k}": v for k, v in params.items()},
                 **{f"b:{k}": v for k, v in buffers.items()}}
        flat_in, in_tree = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arr_in = [x._data if isinstance(x, Tensor) else x for x in flat_in]
        tensor_pos = [i for i, x in enumerate(flat_in) if isinstance(x, Tensor)]
        key = self._key(arr_in)
        if key in self._graph_broken:
            return self._fn(*args, **kwargs)
        if key in self._guarded:
            return self._run_guarded(key, state, flat_in, in_tree,
                                     tensor_pos, arr_in, args, kwargs)

        if key not in self._cache:
            self._cache[key] = self._build_pure(state, flat_in, in_tree,
                                                tensor_pos)
            self._maybe_dump_ir(key, state, arr_in, tensor_pos)

        state_arrays = {k: t._data for k, t in state.items()}
        dyn = [arr_in[i] for i in tensor_pos]
        try:
            out_arrays, new_state = self._cache[key](
                state_arrays, _rng.next_key(), *dyn)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError):
            del self._cache[key]
            # SOT-lite: tensor-dependent `if` — record the branch-decision
            # vector eagerly, then compile a per-branch specialization with
            # value guards (reference capability: jit/sot re-traces per
            # guarded branch, translate.py:106). Non-bool concretizations
            # (int shapes etc.) still graph-break.
            from ..core import branch_guards as _bg
            with _bg.record() as rec:
                out = self._fn(*args, **kwargs)
            decisions = rec.decisions
            if not decisions:
                import warnings
                warnings.warn(
                    f"jit.to_static({getattr(self._fn, '__name__', self._fn)}): "
                    "tensor-dependent Python control flow cannot be "
                    "captured — falling back to eager for this input "
                    "signature (use paddle.where or static shapes)",
                    stacklevel=2)
                self._graph_broken.add(key)
                return out
            self._warn_loop_sites(rec.loop_sites)
            from collections import OrderedDict
            entry = {"specs": OrderedDict(), "last": decisions}
            entry["specs"][decisions] = self._build_pure(
                state, flat_in, in_tree, tensor_pos, decisions)
            self._guarded[key] = entry
            return out    # eager result this call; compiled from the next
        # commit buffer mutations (running stats etc.); params are read-only here
        for k, t in state.items():
            if k.startswith("b:"):
                t._data = new_state[k]
        return _tree_to_tensors(out_arrays)

    def _build_pure(self, state, flat_in, in_tree, tensor_pos,
                    decisions=None):
        """jit the functionalized eager call. With ``decisions``, the trace
        replays that branch-decision vector at every tensor bool and the
        condition values ride along as guard outputs."""
        from ..core import branch_guards as _bg

        installer = _Installed(state)
        # template keeps only non-tensor leaves; tensor slots are filled
        # from dyn_args each call (so no input batch is pinned in HBM)
        template = [None if isinstance(x, Tensor) else x for x in flat_in]

        def pure(state_arrays, rng_key, *dyn_args):
            with installer:
                installer.install(state_arrays)
                with _rng.capture_rng(rng_key), _ag.no_grad():
                    vals = list(template)
                    for i, a in zip(tensor_pos, dyn_args):
                        vals[i] = a
                    a_args, a_kwargs = jax.tree.unflatten(in_tree, [
                        Tensor(v) if i in tensor_pos else v
                        for i, v in enumerate(vals)])
                    if decisions is None:
                        out = self._fn(*a_args, **a_kwargs)
                        conds = None
                    else:
                        with _bg.replay(decisions) as rp:
                            out = self._fn(*a_args, **a_kwargs)
                        conds = tuple(
                            jnp.reshape(jnp.asarray(c), ()).astype(bool)
                            for c in rp.conds)
                new_state = installer.current()
            out_arrays = jax.tree.map(
                lambda x: x._data if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            if decisions is None:
                return out_arrays, new_state
            return out_arrays, new_state, conds

        return jax.jit(pure)

    def _run_guarded(self, key, state, flat_in, in_tree, tensor_pos,
                     arr_in, args, kwargs):
        """Dispatch among branch specializations.

        Run the last-used specialization; its guard outputs are the
        condition values computed on the CURRENT inputs, so the first
        guard that disagrees with the specialization's decision vector
        reveals the true branch — dispatch to (or record+compile) the
        right specialization instead of permanent eager fallback.
        """
        from ..core import branch_guards as _bg

        entry = self._guarded[key]
        state_arrays = {k: t._data for k, t in state.items()}
        dyn = [arr_in[i] for i in tensor_pos]
        vec = entry["last"]
        tried = set()
        for _ in range(len(entry["specs"]) + 1):
            tried.add(vec)
            try:
                out_arrays, new_state, conds = entry["specs"][vec](
                    state_arrays, _rng.next_key(), *dyn)
            except _bg.GuardOverflow:
                # the branch STRUCTURE is input-dependent beyond value
                # specialization — drop the spec and re-record
                del entry["specs"][vec]
                break
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError):
                # a NON-bool concretization inside a guarded branch: value
                # guards cannot capture it — graph-break like the plain
                # path (the removed-fallback regression)
                import warnings
                warnings.warn(
                    f"jit.to_static({getattr(self._fn, '__name__', self._fn)}): "
                    "tensor-dependent Python control flow cannot be "
                    "captured — falling back to eager for this input "
                    "signature (use paddle.where or static shapes)",
                    stacklevel=2)
                self._graph_broken.add(key)
                del self._guarded[key]
                return self._fn(*args, **kwargs)
            observed = tuple(bool(c) for c in conds)
            if observed == vec:
                entry["last"] = vec
                if hasattr(entry["specs"], "move_to_end"):
                    entry["specs"].move_to_end(vec)   # LRU recency
                for k, t in state.items():
                    if k.startswith("b:"):
                        t._data = new_state[k]
                return _tree_to_tensors(out_arrays)
            # first divergent guard is computed on the shared prefix path,
            # so its value is the true decision
            k_div = next((i for i, (o, v) in enumerate(zip(observed, vec))
                          if o != v), None)
            if k_div is None:
                break    # lengths diverged: structure mismatch, re-record
            prefix = vec[:k_div] + (observed[k_div],)
            matches = [v for v in entry["specs"]
                       if v[:k_div + 1] == prefix and v not in tried]
            if matches:
                vec = matches[0]   # refine along any consistent candidate
                continue
            break
        # unknown branch path: eager run records it; compile for next time
        with _bg.record() as rec:
            out = self._fn(*args, **kwargs)
        decisions = rec.decisions
        if decisions and decisions not in entry["specs"]:
            self._warn_loop_sites(rec.loop_sites)
            entry["specs"][decisions] = self._build_pure(
                state, flat_in, in_tree, tensor_pos, decisions)
            # bounded specialization cache with LRU eviction (round-3
            # verdict item 5: k independent branches can demand 2^k specs;
            # a data-dependent Python loop demands one per trip count)
            from ..core.flags import GLOBAL_FLAGS
            bound = max(int(GLOBAL_FLAGS.get(
                "sot_specialization_cache_size")), 1)
            while len(entry["specs"]) > bound:
                entry["specs"].popitem(last=False)
        if decisions:
            entry["last"] = decisions
        return out

    def _maybe_dump_ir(self, key, state, arr_in, tensor_pos):
        """FLAGS_logging_pir_py_code_dir: dump the jaxpr text of each
        newly-compiled specialization (the reference's PIR py-code dump,
        logging_utils; jaxpr/StableHLO is the IR on this stack)."""
        from ..core.flags import GLOBAL_FLAGS
        out_dir = GLOBAL_FLAGS.get("logging_pir_py_code_dir")
        if not out_dir:
            return
        try:
            import os
            os.makedirs(out_dir, exist_ok=True)
            state_arrays = {k: t._data for k, t in state.items()}
            dyn = [arr_in[i] for i in tensor_pos]
            # constant key: a debug dump must not advance the global RNG
            # stream (that would change model numerics when the flag is on)
            dump_key = jax.random.PRNGKey(0)
            jaxpr = jax.make_jaxpr(self._cache[key]._fun
                                   if hasattr(self._cache[key], "_fun")
                                   else self._cache[key])(
                state_arrays, dump_key, *dyn)
            name = getattr(self._fn, "__name__", "fn")
            path = os.path.join(
                out_dir, f"{name}_{abs(hash(key)) & 0xFFFFFFFF:08x}.jaxpr")
            # jaxpr text renders constants as names only; append a consts
            # section so the dump is self-contained, with
            # FLAGS_logging_pir_py_code_int_tensor_element_limit bounding
            # how many elements each constant renders.
            # FLAGS_logging_trunc_pir_py_code caps the dump file itself.
            import numpy as _np
            limit = int(GLOBAL_FLAGS.get(
                "logging_pir_py_code_int_tensor_element_limit"))
            text = str(jaxpr)
            if getattr(jaxpr, "consts", None):
                lines = ["", "consts:"]
                for i, c in enumerate(jaxpr.consts):
                    a = _np.asarray(c)
                    body = _np.array2string(
                        a, threshold=max(limit, 1),
                        edgeitems=max(limit // 2, 1))
                    lines.append(f"  c{i}: {a.dtype}{list(a.shape)} = {body}")
                text += "\n".join(lines) + "\n"
            if GLOBAL_FLAGS.get("logging_trunc_pir_py_code") \
                    and len(text) > 65536:
                text = text[:65536] + "\n... [truncated by " \
                    "FLAGS_logging_trunc_pir_py_code]\n"
            with open(path, "w") as f:
                f.write(text)
        except Exception:
            pass  # a debug dump must never break the compile path

    def _warn_loop_sites(self, loop_sites):
        """One-time hint when a capture shows a tensor-dependent LOOP:
        value guards compile one specialization per trip count; the O(1)
        compile path is paddle.static.nn.while_loop (lax.while_loop)."""
        if not loop_sites:
            return
        warned = getattr(self, "_loop_warned", set())
        self._loop_warned = warned
        for site, n in loop_sites.items():
            if site in warned or n < 4:
                continue
            warned.add(site)
            from ..core.vlog import vlog
            vlog(0, f"to_static: tensor-dependent loop at {site[0]}:"
                    f"{site[1]} ({n} iterations) compiles one "
                    "specialization per trip count; rewrite with "
                    "paddle.static.nn.while_loop to compile once",
                 component="jit")

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """``paddle.jit.to_static`` analog (reference: python/paddle/jit/api.py:197)."""

    def deco(fn):
        from ..nn import Layer
        if isinstance(fn, Layer):
            layer = fn
            static = StaticFunction(layer.forward, layer)
            layer.forward = static
            return layer
        return StaticFunction(fn, None)

    if function is None:
        return deco
    return deco(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """Fused compiled training step.

    Traces the *eager* engine — forward, tape backward, optimizer — into one
    XLA executable. Parameter and optimizer-state buffers are donated so
    updates are in-place in HBM (the reference needs fused multi-tensor
    kernels + interpreter scheduling for the same effect, SURVEY.md §3.3).

    ``accumulate_steps=K`` runs micro-batch gradient accumulation INSIDE
    the compiled step: the batch splits into K equal micro-batches along
    axis 0 and a ``lax.scan`` threads a dtype-bucketed flat gradient
    accumulator through K forward+backward replays (the body is traced
    once — HLO stays O(1) in K), then applies ONE optimizer update from
    the mean gradients. The accumulator never leaves the device and the
    host still issues exactly one dispatch per optimizer step, so a K×
    effective batch fits in the activation memory of a batch/K step.
    Numerically the update equals a single K×-batch step for mean-shaped
    losses (micro means averaged over K).

    ``remat_policy`` pins the activation rematerialization policy
    ('none' / 'dots_saveable' / 'full', see FLAGS_remat_policy) for this
    step's traces; None defers to the flag.

    Usage::
        step = TrainStep(model, lambda x, y: F.cross_entropy(model(x), y), opt)
        loss = step(x_batch, y_batch)
    """

    def __init__(self, model, loss_fn, optimizer, accumulate_steps=1,
                 remat_policy=None, sharding=None, capture_hlo=False):
        from ..nn.scan_stack import REMAT_POLICIES
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.accumulate_steps = int(accumulate_steps)
        if self.accumulate_steps < 1:
            raise ValueError("accumulate_steps must be >= 1")
        if remat_policy is not None and remat_policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {REMAT_POLICIES} or None, "
                f"got {remat_policy!r}")
        self.remat_policy = remat_policy
        # GSPMD partitioning (distributed/gspmd.py): DP/TP/ZeRO as
        # NamedSharding annotations over one (data, model) mesh, applied
        # as in/out_shardings of THIS step's one jax.jit — an explicit
        # ShardingConfig pins the regime, None defers to FLAGS_gspmd.
        from ..distributed import gspmd as _gspmd
        if sharding is not None and not isinstance(
                sharding, _gspmd.ShardingConfig):
            sharding = _gspmd.ShardingConfig.parse(str(sharding))
        self.sharding = sharding
        #: HLO forensics of the most recent forensics-captured compile:
        #: the full module text + its collective-op counts. Captured for
        #: every GSPMD-annotated compile (the collective-mix gates need
        #: it) and, with ``capture_hlo=True``, for unsharded compiles
        #: too (the fusion-forensics probe's surface — one extra
        #: lower+compile per first call, so it stays opt-in). None until
        #: a captured specialization has been built.
        self.capture_hlo = bool(capture_hlo)
        self.last_hlo_text = None
        self.last_hlo_collectives = None
        # compile forensics: wall-ms of the most recent first-call
        # trace+lower+build, and the running total across re-specializes
        # (shape changes, flag flips). Mirrored into bench.py artifacts.
        self.last_compile_ms = None
        self.compile_ms_total = 0.0
        self._cache = {}
        self._compiled_keys = set()
        # materialize optimizer state now so it traces as inputs
        params = [p for p in optimizer._parameter_list if not p.stop_gradient]
        self._params = {f"p{i}": p for i, p in enumerate(params)}
        # positional key -> model parameter name: the GSPMD rule table is
        # name-driven (q_proj/o_proj/embed/...), while the step's pytree
        # keys are positional. LayerStack leaves keep their "stacked."
        # marker (the Parameter's own name, not the attribute path) so
        # the pp=K stage-slicing rule can recognize the [L, ...] layout.
        by_id = {}
        if hasattr(model, "named_parameters"):
            by_id = {id(p): ("stacked." + n
                             if str(getattr(p, "name", "")
                                    ).startswith("stacked.") else n)
                     for n, p in model.named_parameters()}
        self._param_names = {k: by_id.get(id(p), k)
                             for k, p in self._params.items()}

    def _fused_eng(self):
        eng = getattr(self.optimizer, "_fused_engine", None)
        return eng if (eng is not None and eng.active) else None

    def _opt_state_arrays(self):
        eng = self._fused_eng()
        if eng is not None:
            # fused path: optimizer state IS the engine's flat per-bucket
            # buffers — O(#dtype buckets) donated inputs, not O(n_params)
            return eng.state_arrays()
        out = {}
        for i, p in self._params.items():
            st = self.optimizer._state.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{i}.{k}"] = v
        return out

    def _install_opt_state(self, arrays):
        eng = self._fused_eng()
        if eng is not None:
            eng.install_state(arrays)
            return
        for i, p in self._params.items():
            st = {}
            prefix = f"{i}."
            for k, v in arrays.items():
                if k.startswith(prefix):
                    st[k[len(prefix):]] = v
            if st:
                self.optimizer._state[id(p)] = st

    def __call__(self, *batch):
        from ..core.flags import GLOBAL_FLAGS
        from ..io.prefetch import PIPELINE_METRICS
        from ..nn.scan_stack import remat_policy_scope, effective_remat_policy
        _, buffers = _collect_state(self.model)
        for b in batch:
            if isinstance(b, Tensor) and getattr(b, "_donated", False):
                raise RuntimeError(
                    "TrainStep received a batch tensor whose buffer was "
                    "already donated to a previous compiled step. Staged "
                    "batches (DataLoader(use_buffer_reader=True)) are "
                    "single-use on TPU; to reuse a batch across steps, "
                    "pass your own tensor or set use_buffer_reader=False.")
        batch_arrays = tuple(b._data if isinstance(b, Tensor) else jnp.asarray(b)
                             for b in batch)
        K = self.accumulate_steps
        if K > 1:
            if buffers:
                raise RuntimeError(
                    "TrainStep(accumulate_steps>1) cannot scan a model "
                    "with registered buffers: per-micro-batch buffer "
                    "mutations cannot be committed from a scan body. Use "
                    "accumulate_steps=1 (or an outer accumulation loop) "
                    "for buffer-mutating models.")
            if any(not a.shape or a.shape[0] % K for a in batch_arrays):
                # ragged tail batch (drop_last=False loaders): process it
                # as ONE micro-batch — the mean-grad update is identical
                # to accumulating it in smaller pieces, and the odd shape
                # re-specializes the step anyway. Warn once so a loader
                # that NEVER divides doesn't silently disable
                # accumulation for the whole run.
                if not getattr(self, "_warned_ragged", False):
                    import warnings
                    self._warned_ragged = True
                    warnings.warn(
                        f"TrainStep(accumulate_steps={K}): batch axis 0 "
                        f"{[tuple(a.shape) for a in batch_arrays]} is not "
                        f"divisible by {K}; running this batch without "
                        "accumulation (expected for a drop_last=False "
                        "tail batch — if every batch hits this, fix the "
                        "batch size)", stacklevel=2)
                K = 1
        check_finite = bool(GLOBAL_FLAGS.get("check_nan_inf"))
        # remat enters the cache key: the policy is baked into the traced
        # program (jax.checkpoint over the scanned body), so a flag flip
        # must re-specialize rather than reuse a stale executable. The
        # explicit TrainStep override pins a scope for the trace; without
        # one the model resolves the flag (and its own config.remat).
        remat = self.remat_policy or effective_remat_policy()
        from contextlib import nullcontext
        policy_ctx = (remat_policy_scope(self.remat_policy)
                      if self.remat_policy else nullcontext())
        # Staged-batch donation: batches the prefetch pipeline put on the
        # device (io/prefetch.py marks them _staged_h2d) are consumed
        # exactly once, so their buffers can be given back to XLA — the
        # step reuses the HBM instead of allocating fresh activations next
        # to a dead input copy. A caller-owned tensor (e.g. the bench
        # reusing one batch) is never donated.
        donate_batch = bool(batch) and jax.default_backend() != "cpu" and \
            all(isinstance(b, Tensor) and getattr(b, "_staged_h2d", False)
                for b in batch)
        from ..distributed import gspmd as _gspmd
        shard_cfg = self.sharding or _gspmd.config_from_flags()
        if shard_cfg is not None:
            shard_cfg = shard_cfg.resolve()
        pipe_M = 0
        if shard_cfg is not None and shard_cfg.pipe > 1:
            pipe_M = int(GLOBAL_FLAGS.get("pipeline_microbatches")) \
                or shard_cfg.pipe
            self._validate_pipeline(shard_cfg, batch_arrays, pipe_M)
        cfg_key = None if shard_cfg is None else \
            (shard_cfg.data, shard_cfg.model, shard_cfg.zero,
             shard_cfg.pipe, pipe_M)
        key = tuple((a.shape, str(a.dtype)) for a in batch_arrays) \
            + (check_finite, donate_batch, K, remat, cfg_key)

        if key not in self._cache:
            # Ensure optimizer state exists with final shapes: run one throwaway
            # state init by touching _param_state via a zero-grad apply is
            # avoided; instead let the traced call create state lazily inside
            # the trace — it becomes constants. To keep state as *inputs*, we
            # pre-create it here by calling the state initializer explicitly.
            self._prime_state()
            param_t = dict(self._params)
            buffer_t = {f"b:{k}": v for k, v in buffers.items()}
            opt = self.optimizer
            model = self.model
            loss_fn = self.loss_fn
            step_holder = {}
            mesh = None
            batch_sh = None
            if shard_cfg is not None:
                mesh = _gspmd.build_mesh(shard_cfg)
                self._mesh = mesh
                batch_sh = tuple(_gspmd.batch_sharding(a, mesh)
                                 for a in batch_arrays)

            def pure_step(param_arrays, opt_arrays, buffer_arrays, step_i, lr, rng, *b_arrays):
                if mesh is not None:
                    # pin the data-parallel batch split inside the traced
                    # program too (in_shardings place the inputs; the
                    # constraint stops the partitioner from re-replicating
                    # the batch into the forward)
                    b_arrays = tuple(
                        jax.lax.with_sharding_constraint(b, sh)
                        for b, sh in zip(b_arrays, batch_sh))
                inst_p = _Installed(param_t)
                inst_b = _Installed(buffer_t)
                saved_state = {pid: dict(st) for pid, st in opt._state.items()}
                eng = getattr(opt, "_fused_engine", None)
                saved_eng = eng.snapshot() if eng is not None and eng.active \
                    else None
                saved_step, saved_lr = opt._step_count, opt._lr
                saved_grads = {k: p.grad for k, p in param_t.items()}
                try:
                    with inst_p, inst_b, _rng.capture_rng(rng):
                        inst_p.install(param_arrays)
                        inst_b.install(buffer_arrays)
                        self._install_opt_state(opt_arrays)
                        opt._step_count = step_i
                        opt._lr = lr
                        for p in param_t.values():
                            p.grad = None
                        if K == 1:
                            batch_tensors = [Tensor(a) for a in b_arrays]
                            loss = loss_fn(*batch_tensors)
                            loss.backward()
                            loss_arr = loss._data
                        else:
                            loss_arr = self._accumulate_grads(
                                loss_fn, param_t, b_arrays, K, rng)
                        opt.step()
                        new_params = inst_p.current()
                        new_buffers = inst_b.current()
                        new_opt = self._opt_state_arrays()
                        if check_finite:
                            # compiled-path numerical sanitizer (reference:
                            # new_executor/nan_inf_utils.h under
                            # FLAGS_check_nan_inf): one fused all-finite
                            # reduction over loss + updated params, checked
                            # host-side — no per-op sync like the eager sweep
                            import jax.numpy as _jnp
                            finite = _jnp.isfinite(loss_arr).all()
                            for v in new_params.values():
                                if _jnp.issubdtype(v.dtype, _jnp.inexact):
                                    finite &= _jnp.isfinite(v).all()
                            return new_params, new_opt, new_buffers, \
                                loss_arr, finite
                        return new_params, new_opt, new_buffers, loss_arr
                finally:
                    opt._state = saved_state
                    if saved_eng is not None:
                        eng.restore(saved_eng)
                    opt._step_count, opt._lr = saved_step, saved_lr
                    for k, p in param_t.items():
                        p.grad = saved_grads[k]

            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            if donate_batch:
                # b_arrays start after the 6 fixed args of pure_step
                donate = donate + tuple(range(6, 6 + len(batch_arrays)))
            jit_kw = {}
            if mesh is not None:
                # GSPMD: the regime IS this annotation set — params by
                # the name-driven rule table, fused flat optimizer
                # buckets on the data axis under ZeRO (per-param state
                # mirrors its param), batch on data, scalars/rng/buffers
                # replicated. Identical in/out shardings keep the
                # param/opt donation valid on TPU.
                p_sh = _gspmd.named_param_shardings(
                    {k: (self._param_names[k], tuple(p._data.shape))
                     for k, p in self._params.items()}, mesh)
                o_sh = _gspmd.opt_state_shardings(
                    self._opt_state_arrays(), p_sh, mesh,
                    zero=shard_cfg.zero)
                b_sh = {k: _gspmd.replicated(mesh) for k in buffer_t}
                rep = _gspmd.replicated(mesh)
                out_sh = (p_sh, o_sh, b_sh, rep)
                if check_finite:
                    out_sh = out_sh + (rep,)
                jit_kw = dict(
                    in_shardings=(p_sh, o_sh, b_sh, rep, rep, rep)
                    + batch_sh,
                    out_shardings=out_sh)
            self._cache[key] = jax.jit(pure_step, donate_argnums=donate,
                                       **jit_kw)

        param_arrays = {k: p._data for k, p in self._params.items()}
        opt_arrays = self._opt_state_arrays()
        buffer_arrays = {f"b:{k}": v._data for k, v in buffers.items()}
        lr = self.optimizer.get_lr()
        step_in = self.optimizer._step_count  # inside-trace step() adds 1
        rng_key = _rng.next_key()
        eager_loss = None
        if GLOBAL_FLAGS.get("enable_cinn_accuracy_check") \
                and key not in getattr(self, "_accuracy_checked", set()):
            # FLAGS_enable_cinn_accuracy_check (reference flags.cc): once
            # per compiled specialization, recompute the loss through the
            # EAGER engine on the same params + rng key and compare within
            # the accuracy_check_* tolerances — catches a compiled-path
            # lowering that silently diverges from eager. Runs BEFORE the
            # compiled call: on TPU the compiled step donates the param /
            # opt-state buffers, so reading them afterwards would hit
            # deleted arrays. Buffer bindings mutated by the eager forward
            # (e.g. running stats) are restored — the compiled step's
            # updates are the ones that count.
            self._accuracy_checked = getattr(self, "_accuracy_checked", set())
            self._accuracy_checked.add(key)
            saved_buf = {k: t._data for k, t in buffers.items()}
            try:
                with _rng.capture_rng(rng_key):
                    eager_loss = float(self.loss_fn(*batch).numpy())
            finally:
                for k, t in buffers.items():
                    t._data = saved_buf[k]
        PIPELINE_METRICS.record_dispatch()
        first_run = key not in self._compiled_keys
        args = (param_arrays, opt_arrays, buffer_arrays,
                jnp.asarray(step_in, jnp.int32),
                jnp.asarray(lr, jnp.float32), rng_key, *batch_arrays)
        if first_run:
            # first call of this specialization = trace + lower + build:
            # record a `compile` span on the profiler timeline so a
            # recompile (shape change, remat/flag flip) is visible next
            # to the pipeline gauges instead of reading as one slow step.
            from ..profiler import compile_event
            shard_ctx = (_gspmd.partitioning_scope(self._mesh)
                         if shard_cfg is not None else nullcontext())
            # pp>1: LayerStack.forward switches to the stage-sliced
            # pipelined scan while this scope is bound around the trace
            pipe_ctx = (_gspmd.pipeline_scope(
                self._mesh, shard_cfg.pipe, pipe_M)
                if shard_cfg is not None and shard_cfg.pipe > 1
                else nullcontext())
            if shard_cfg is not None or self.capture_hlo:
                # HLO forensics: keep the compiled module + its
                # collective mix inspectable (tests/test_gspmd.py,
                # probe_gspmd; jit/hlo_forensics.py fusion stats via
                # probe_hlo_fusion). One extra lower+compile, paid only
                # on the first call of a sharded (or capture_hlo)
                # specialization.
                try:
                    with policy_ctx, shard_ctx, pipe_ctx:
                        hlo = self._cache[key].lower(*args).compile() \
                            .as_text()
                    self.last_hlo_text = hlo
                    self.last_hlo_collectives = \
                        _gspmd.collective_counts(hlo)
                except Exception:
                    self.last_hlo_text = None
                    self.last_hlo_collectives = None
            with policy_ctx, shard_ctx, pipe_ctx, compile_event(
                    f"TrainStep(K={K},remat={remat})") as ev:
                out = self._cache[key](*args)
            self._compiled_keys.add(key)
            self.last_compile_ms = ev.ms
            self.compile_ms_total += ev.ms
        else:
            with policy_ctx:
                out = self._cache[key](*args)
        if donate_batch:
            for b in batch:
                # buffer handed to XLA: mark so a reuse raises our error
                # above instead of jax's opaque "Array has been deleted"
                b._staged_h2d = False
                b._donated = True
        if check_finite:
            new_p, new_o, new_b, loss, finite = out
            if not bool(finite):
                raise FloatingPointError(
                    f"NaN/Inf detected in compiled train step "
                    f"{self.optimizer._step_count} (FLAGS_check_nan_inf)")
        else:
            new_p, new_o, new_b, loss = out
        if eager_loss is not None:
            compiled_loss = float(jnp.asarray(loss))
            # no `or`-defaults: an explicit 0 tolerance must stay 0
            rtol = float(GLOBAL_FLAGS.get("accuracy_check_rtol_fp32"))
            atol = float(GLOBAL_FLAGS.get("accuracy_check_atol_fp32"))
            self.last_accuracy_check = {
                "eager": eager_loss, "compiled": compiled_loss}
            if abs(eager_loss - compiled_loss) > atol + rtol * abs(eager_loss):
                raise FloatingPointError(
                    f"compiled/eager loss mismatch (FLAGS_enable_cinn_"
                    f"accuracy_check): eager {eager_loss} vs compiled "
                    f"{compiled_loss} (rtol {rtol}, atol {atol})")
        self.optimizer._step_count += 1
        for k, p in self._params.items():
            p._data = new_p[k]
        self._install_opt_state(new_o)
        for k, t in buffers.items():
            t._data = new_b[f"b:{k}"]
        return Tensor(loss)

    def _accumulate_grads(self, loss_fn, param_t, b_arrays, K, rng):
        """Micro-batch gradient accumulation inside the traced step.

        Splits each batch array into K equal micro-batches along axis 0
        and ``lax.scan``s one forward+backward per micro-batch — the tape
        replay is traced ONCE, so HLO stays O(1) in K. The carry is a
        dtype-bucketed FLAT gradient accumulator (one buffer per param
        dtype, the layout the fused optimizer's buckets consume), plus
        the running loss; XLA double-buffers the carry in place across
        iterations, so the accumulator never leaves the device. On exit
        the mean grads are sliced back onto ``p.grad`` and the caller
        runs ONE optimizer update — host dispatches per optimizer step
        are unchanged from K=1.

        Participation mirrors the K=1 path: an abstract probe
        (``jax.eval_shape`` of one micro-batch's forward+backward, no
        FLOPs) discovers which params actually receive a gradient and
        with what dtype; non-participating params keep ``grad=None`` so
        the optimizer skips them exactly like a single K×-batch step
        would (no fabricated zero grads feeding weight decay / moments).
        Each micro-batch re-seeds the captured RNG stream with its scan
        index so stateful randomness (dropout) would not replay one
        traced key K times.
        """
        import numpy as _np

        order = [(k, p) for k, p in param_t.items()
                 if jnp.issubdtype(jnp.result_type(p._data), jnp.inexact)]
        micro = tuple(
            a.reshape((K, a.shape[0] // K) + tuple(a.shape[1:]))
            for a in b_arrays)

        def _probe(mbs):
            for _, p in order:
                p.grad = None
            try:
                with _rng.capture_rng(jax.random.fold_in(rng, 0)):
                    loss = loss_fn(*[Tensor(a) for a in mbs])
                    loss.backward()
                return {name: p.grad._data for name, p in order
                        if p.grad is not None}
            finally:
                for _, p in order:
                    p.grad = None

        grad_shapes = jax.eval_shape(
            _probe, tuple(jax.ShapeDtypeStruct(m.shape[1:], m.dtype)
                          for m in micro))
        groups: dict = {}
        for name, p in order:
            if name not in grad_shapes:
                continue  # never receives a grad: optimizer skips it
            aval = grad_shapes[name]
            shape = tuple(aval.shape)
            groups.setdefault(str(aval.dtype), []).append(
                (name, int(_np.prod(shape)) if shape else 1, shape,
                 aval.dtype))
        init = ({dts: jnp.zeros(sum(e[1] for e in g), jnp.dtype(dts))
                 for dts, g in groups.items()},
                jnp.zeros((), jnp.float32))

        def body(carry, xs):
            acc, loss_acc = carry
            idx, mbs = xs[0], xs[1:]
            for _, p in order:
                p.grad = None
            with _rng.capture_rng(jax.random.fold_in(rng, idx)):
                loss = loss_fn(*[Tensor(a) for a in mbs])
                loss.backward()
            new_acc = {}
            from ..distributed.gspmd import constrain_flat
            for dts, g in groups.items():
                parts = []
                for name, sz, _, dt in g:
                    grad = param_t[name].grad
                    parts.append(constrain_flat(
                        jnp.ravel(grad._data).astype(dt))
                        if grad is not None else jnp.zeros(sz, dt))
                flat = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)
                new_acc[dts] = acc[dts] + flat
            for _, p in order:
                p.grad = None
            return (new_acc, loss_acc + loss._data.astype(jnp.float32)), None

        (acc, loss_sum), _ = jax.lax.scan(
            body, init, (jnp.arange(K),) + micro)
        from ..distributed.gspmd import constrain_flat
        for dts, g in groups.items():
            flat = acc[dts] / K
            off = 0
            for name, sz, shape, _ in g:
                param_t[name].grad = Tensor(
                    constrain_flat(jax.lax.slice_in_dim(
                        flat, off, off + sz)).reshape(shape),
                    stop_gradient=True)
                off += sz
        return loss_sum / K

    def _validate_pipeline(self, shard_cfg, batch_arrays, pipe_M):
        """pp=K preconditions, checked before the cache key so a bad
        preset fails loudly instead of replicating silently: K must
        divide both the device count left after dp x tp AND the model's
        scan-stacked layer count; the microbatch count M must divide
        the batch dim."""
        pipe = shard_cfg.pipe
        n = len(jax.devices())
        per_pp = n // (shard_cfg.data * shard_cfg.model)
        stack_layers = sorted({
            int(p._data.shape[0]) for k, p in self._params.items()
            if "stacked." in self._param_names.get(k, "")
            and p._data.ndim >= 2})
        bad_stack = (not stack_layers
                     or any(l % pipe for l in stack_layers))
        if per_pp % pipe or bad_stack:
            layers = stack_layers[0] if stack_layers else 0
            raise ValueError(
                f"gspmd 'pp={pipe}': the pipeline degree must divide "
                f"both the device count after dp x tp "
                f"({per_pp} = {n} devices / dp={shard_cfg.data} / "
                f"tp={shard_cfg.model}) and the model's scan-stacked "
                f"layer count ({layers}; 0 = no LayerStack — enable "
                f"FLAGS_scan_layers); got pp={pipe}, {per_pp} devices, "
                f"{layers} layers")
        for a in batch_arrays:
            if a.ndim >= 1 and a.shape[0] % pipe_M:
                raise ValueError(
                    f"gspmd 'pp={pipe}': microbatch count M={pipe_M} "
                    f"(FLAGS_pipeline_microbatches, 0 = auto = pp) must "
                    f"divide the batch dim {a.shape[0]}")

    def _prime_state(self):
        """Create optimizer state ahead of tracing so state rides as
        donated inputs rather than baked constants. Fused optimizers build
        their dtype buckets instead (flat state, O(#buckets) inputs); the
        per-param schema priming is the fallback."""
        params = list(self._params.values())
        if self.optimizer._prime_fused(params):
            return
        for p in params:
            self.optimizer._param_state(p)


def save(layer, path, input_spec=None, **config):
    """``paddle.jit.save`` analog: persist weights + (when exportable) the
    serialized compiled program via jax.export
    (reference: python/paddle/jit/api.py save → TranslatedLayer artifacts)."""
    from ..framework.io import save as fsave
    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    fsave({"state_dict": state, "format": "paddle_tpu.jit.v1"}, path + ".pdparams")


def load(path, **config):
    from ..framework.io import load as fload
    return fload(path + ".pdparams")


def enable_to_static(flag=True):
    pass


def ignore_module(modules):
    pass

from .save_load import save, load, InputSpec, TranslatedLayer  # noqa: F401,E402


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static transcription verbosity (reference: jit/api.py
    set_verbosity -> TranslatorLogger): maps onto FLAGS_v so the vlog
    tier carries SOT diagnostics."""
    from ..core.flags import GLOBAL_FLAGS
    GLOBAL_FLAGS.set("v", int(level))


def set_code_level(level=100, also_to_stdout=False):
    """Dump transformed code up to ``level`` (reference: jit/api.py
    set_code_level). The SOT-lite pipeline has one transform stage, so any
    level >= 1 turns on specialization-dump logging via
    FLAGS_logging_pir_py_code_dir default '.' when unset."""
    from ..core.flags import GLOBAL_FLAGS
    if int(level) >= 1 and not GLOBAL_FLAGS.get("logging_pir_py_code_dir"):
        GLOBAL_FLAGS.set("logging_pir_py_code_dir", ".")
