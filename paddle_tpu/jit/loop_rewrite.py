"""AST-level auto-rewrite of tensor-dependent Python ``while`` loops.

The reference compiles a plain ``while bool(tensor):`` loop transparently
through its SOT bytecode VM + loop transformer (reference:
python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py;
python/paddle/jit/dy2static/transformers/loop_transformer.py). The
TPU-native equivalent works at the SOURCE level: ``to_static`` parses the
function, rewrites each *safe* ``while`` statement into a call to
:func:`auto_while`, and ``auto_while`` decides at run time:

- condition is a plain Python bool  -> ordinary Python loop (unchanged
  semantics, zero overhead beyond one call frame);
- condition is a Tensor, gradients cannot flow, and the loop state is
  carriable -> ONE ``lax.while_loop`` via ``static.control_flow
  .while_loop`` — the loop compiles once for every trip count;
- anything else (shape-variant state, grad-requiring state, un-carriable
  objects) -> Python loop again, which lands in the existing SOT-lite
  value-guard machinery (one specialization per trip count) exactly as
  before the rewrite.

The *safe subset* a ``while`` must satisfy to be rewritten (anything else
is left verbatim — never a behavior change, only a missed optimization):

- no ``else:`` clause, no ``break``/``continue``/``return``/``yield``
  inside the body;
- body statements are assignments to plain names (``x = ...``,
  ``x, y = ...``, ``x += ...``) and ``if``/``elif``/``else`` blocks of the
  same shape — no attribute/subscript stores, no bare expression
  statements (those exist only for side effects), no nested loops, no
  ``global``/``nonlocal``.

Loop state = every name STORED in the body (condition/body reads of
other names resolve through the nested functions' closure over the
enclosing frame). If any state name is unbound when the loop is
reached, the generated code falls back to the verbatim original loop
(kept as a sibling branch), preserving NameError/first-iteration-binds
semantics.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

_HELPER = "__ptpu_auto_while__"


# ---------------------------------------------------------------------------
# runtime helper
# ---------------------------------------------------------------------------

def _grads_may_flow(state, cond_fn, body_fn):
    """True when taking the non-differentiable lax path could sever a
    gradient: any grad-requiring Tensor in the loop state OR reachable
    through the cond/body closures (a Layer's parameters, a captured
    weight). Unknown closure objects count as unsafe — the Python-loop
    fallback is always semantically correct."""
    from ..core.tensor import Tensor

    def tensor_unsafe(v):
        return isinstance(v, Tensor) and not v.stop_gradient

    if any(tensor_unsafe(v) for v in state):
        return True
    import types as _types
    inert = (bool, int, float, complex, str, bytes, type(None),
             _types.ModuleType, _types.FunctionType,
             _types.BuiltinFunctionType, type)
    for fn in (cond_fn, body_fn):
        for cell in (fn.__closure__ or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if isinstance(v, inert):
                continue
            if isinstance(v, Tensor):
                if tensor_unsafe(v):
                    return True
                continue
            params = getattr(v, "parameters", None)
            if callable(params):
                try:
                    if any(tensor_unsafe(p) for p in v.parameters()):
                        return True
                    continue
                except Exception:
                    return True
            return True          # opaque closure object: assume unsafe
    return False


def auto_while(cond_fn, body_fn, state):
    """Run a rewritten while loop; compile-once when safely possible."""
    from ..core import autograd as _ag
    from ..core.tensor import Tensor

    c = cond_fn(*state)
    if isinstance(c, Tensor):
        grads_flow = _ag.is_grad_enabled() and \
            _grads_may_flow(state, cond_fn, body_fn)
        if not grads_flow:
            carriable = all(
                isinstance(v, (Tensor, bool, int, float)) for v in state)
            if carriable:
                import jax
                import jax.numpy as jnp
                canon = [v if isinstance(v, Tensor)
                         else Tensor(jnp.asarray(v)) for v in state]
                from ..static.control_flow import while_loop
                try:
                    out = while_loop(lambda *s: cond_fn(*s),
                                     lambda *s: list(body_fn(*s)), canon)
                except (ValueError, TypeError):
                    # shape/dtype-variant loop state (e.g. a growing
                    # decode buffer): not lax-compilable — fall through
                    # to the Python loop, the pre-rewrite behavior
                    pass
                else:
                    # restore Python scalar types for state entries we
                    # canonicalized, when concrete (eager) — the loop
                    # must not change a local's type; under trace they
                    # stay Tensors (inherent: the value is now
                    # data-dependent)
                    res = []
                    for orig, o in zip(state, out):
                        if not isinstance(orig, Tensor) and \
                                isinstance(o, Tensor) and \
                                not isinstance(o._data, jax.core.Tracer):
                            res.append(type(orig)(o._data.item()))
                        else:
                            res.append(o)
                    return tuple(res)
    # plain-Python semantics: bool(c) routes through the SOT-lite guard
    # hook under capture, exactly like the original loop did
    while c:
        state = tuple(body_fn(*state))
        c = cond_fn(*state)
    return state


# ---------------------------------------------------------------------------
# safety analysis
# ---------------------------------------------------------------------------

def _stored_names(stmts):
    out = []

    def visit_target(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                visit_target(e)
        else:
            raise _Unsafe()

    for s in stmts:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                visit_target(t)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            if not isinstance(s.target, ast.Name):
                raise _Unsafe()
            out.append(s.target.id)
        elif isinstance(s, ast.If):
            out.extend(_stored_names(s.body))
            out.extend(_stored_names(s.orelse))
        else:
            raise _Unsafe()
    return out


class _Unsafe(Exception):
    pass


def _expr_loads(e):
    return {n.id for n in ast.walk(e)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _target_names(t):
    if isinstance(t, ast.Name):
        return {t.id}
    out = set()
    for e in getattr(t, "elts", ()):
        out |= _target_names(e)
    return out


def _live_in(stmts):
    """Names READ before any store in one pass over a safe-subset body —
    these must be loop-carried; names only written-then-read inside the
    body are pure temporaries and stay body-locals."""
    live = set()

    def walk(stmts, defined):
        for s in stmts:
            if isinstance(s, ast.Assign):
                live.update(_expr_loads(s.value) - defined)
                for t in s.targets:
                    defined |= _target_names(t)
            elif isinstance(s, ast.AugAssign):
                live.update((_expr_loads(s.value) | {s.target.id})
                            - defined)
                defined.add(s.target.id)
            elif isinstance(s, ast.AnnAssign):
                if s.value is not None:
                    live.update(_expr_loads(s.value) - defined)
                defined.add(s.target.id)
            elif isinstance(s, ast.If):
                live.update(_expr_loads(s.test) - defined)
                d1 = walk(s.body, set(defined))
                d2 = walk(s.orelse, set(defined))
                defined |= (d1 & d2)   # definitely-assigned on both arms
        return defined

    walk(stmts, set())
    return live


class _SafetyCheck(ast.NodeVisitor):
    """Reject bodies with control-flow escapes or side-effect statements."""

    def check(self, node):
        try:
            _stored_names(node.body)      # statement-shape check
            for stmt in list(node.body) + [node.test]:
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.Break, ast.Continue, ast.Return,
                                        ast.Yield, ast.YieldFrom, ast.Global,
                                        ast.Nonlocal, ast.While, ast.For,
                                        ast.AsyncFor, ast.Try, ast.With,
                                        ast.NamedExpr)):
                        return False
                    if isinstance(sub, (ast.Attribute, ast.Subscript)) and \
                            isinstance(sub.ctx, (ast.Store, ast.Del)):
                        return False
            return not node.orelse
        except _Unsafe:
            return False


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

class _WhileRewriter(ast.NodeTransformer):
    def __init__(self, outside_loads=None, scope_escapes=None):
        self.counter = 0
        self.rewrote = False
        #: names loaded anywhere in the function OUTSIDE each while —
        #: a body temp read after the loop must stay loop-carried
        self.outside_loads = outside_loads or {}
        #: names declared global/nonlocal in the function: a loop that
        #: stores one cannot be rewritten (the store must reach the
        #: outer scope, which the extracted body_fn cannot do)
        self.scope_escapes = scope_escapes or set()

    # do not descend into nested function/class definitions: only the
    # target function's own loops are rewritten
    def visit_FunctionDef(self, node):
        if getattr(self, "_entered", False):
            return node
        self._entered = True
        self.generic_visit(node)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_While(self, node):
        self.generic_visit(node)     # rewrite inner ifs' loops first
        if not _SafetyCheck().check(node):
            return node
        # loop state = names REBOUND in the body that are also OBSERVED
        # across iterations or outside the loop: read-before-write in
        # the body (carried between trips), read by the condition, or
        # read after the loop. Stored names that fail all three are pure
        # body temporaries and stay body_fn locals — so a fresh temp
        # introduced inside the loop does not force the NameError
        # fallback. Everything else the condition/body reads is
        # loop-invariant and resolves through the nested functions'
        # natural closure over the enclosing frame.
        stored = set(_stored_names(node.body))
        if stored & self.scope_escapes:
            return node
        observed = _live_in(node.body) | _expr_loads(node.test) | \
            self.outside_loads.get(id(node), set())
        names = sorted(stored & observed)
        if not names:
            return node
        n = self.counter
        self.counter += 1
        self.rewrote = True
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        state_tuple = ast.Tuple(
            elts=[ast.Name(id=v, ctx=ast.Load()) for v in names],
            ctx=ast.Load())
        cond_def = ast.FunctionDef(
            name=f"__ptpu_cond_{n}__", args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None, type_comment=None, type_params=[])
        body_def = ast.FunctionDef(
            name=f"__ptpu_body_{n}__", args=args,
            body=list(node.body) + [ast.Return(value=state_tuple)],
            decorator_list=[], returns=None, type_comment=None,
            type_params=[])
        # state snapshot guarded on NameError: an unbound loop var means
        # the original loop's binding semantics must be kept verbatim
        snap = ast.Name(id=f"__ptpu_s_{n}__", ctx=ast.Store())
        try_snap = ast.Try(
            body=[ast.Assign(targets=[snap], value=state_tuple)],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()), name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=f"__ptpu_s_{n}__",
                                      ctx=ast.Store())],
                    value=ast.Constant(value=None))])],
            orelse=[], finalbody=[])
        call = ast.Call(
            func=ast.Name(id=_HELPER, ctx=ast.Load()),
            args=[ast.Name(id=f"__ptpu_cond_{n}__", ctx=ast.Load()),
                  ast.Name(id=f"__ptpu_body_{n}__", ctx=ast.Load()),
                  ast.Name(id=f"__ptpu_s_{n}__", ctx=ast.Load())],
            keywords=[])
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=v, ctx=ast.Store()) for v in names],
                ctx=ast.Store())],
            value=call)
        dispatch = ast.If(
            test=ast.Compare(
                left=ast.Name(id=f"__ptpu_s_{n}__", ctx=ast.Load()),
                ops=[ast.Is()],
                comparators=[ast.Constant(value=None)]),
            body=[node],                 # verbatim original loop
            orelse=[unpack])
        return [cond_def, body_def, try_snap, dispatch]


def rewrite_loops(fn):
    """Return ``fn`` with safe tensor-dependent whiles auto-rewritten, or
    ``fn`` unchanged when the source is unavailable / nothing qualifies.

    Controlled by ``FLAGS_jit_auto_while`` (default on)."""
    from ..core.flags import GLOBAL_FLAGS
    if not GLOBAL_FLAGS.get("jit_auto_while"):
        return fn
    raw_fn = inspect.unwrap(fn)
    if isinstance(raw_fn, functools.partial):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(raw_fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    from collections import Counter
    total = Counter(n.id for n in ast.walk(fdef)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load))
    outside = {}
    for w in ast.walk(fdef):
        if isinstance(w, ast.While):
            inner = Counter(n.id for n in ast.walk(w)
                            if isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Load))
            outside[id(w)] = {k for k, v in total.items()
                              if v - inner.get(k, 0) > 0}
    escapes = set()
    for n in ast.walk(fdef):
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            escapes.update(n.names)
    rw = _WhileRewriter(outside, escapes)
    rw.visit(fdef)
    if not rw.rewrote:
        return fn
    fdef.decorator_list = []
    # strip default expressions (may reference out-of-scope names at exec
    # time); real default objects are re-attached from the original below
    fdef.args.defaults = [ast.Constant(value=None)] * \
        len(fdef.args.defaults)
    fdef.args.kw_defaults = [ast.Constant(value=None) if d is not None
                             else None for d in fdef.args.kw_defaults]
    freevars = raw_fn.__code__.co_freevars
    if freevars:
        # factory pattern re-binds the closure by value (snapshot)
        factory = ast.FunctionDef(
            name="__ptpu_factory__",
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[fdef, ast.Return(value=ast.Name(id=fdef.name,
                                                  ctx=ast.Load()))],
            decorator_list=[], returns=None, type_comment=None,
            type_params=[])
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(mod)
    glb = raw_fn.__globals__
    glb.setdefault(_HELPER, auto_while)
    ns = {}
    try:
        exec(compile(mod, f"<ptpu-loop-rewrite {raw_fn.__qualname__}>",
                     "exec"), glb, ns)
        if freevars:
            cells = [c.cell_contents for c in raw_fn.__closure__]
            new_fn = ns["__ptpu_factory__"](*cells)
        else:
            new_fn = ns[fdef.name]
    except Exception:
        return fn
    new_fn.__defaults__ = raw_fn.__defaults__
    new_fn.__kwdefaults__ = raw_fn.__kwdefaults__
    functools.update_wrapper(new_fn, raw_fn)
    new_fn.__ptpu_loop_rewritten__ = True
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn


__all__ = ["rewrite_loops", "auto_while"]
