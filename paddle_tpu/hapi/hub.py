"""paddle.hub — hubconf-based model loading (reference:
python/paddle/hapi/hub.py list:188 / help:238 / load:286).

A hub repo is a directory with a ``hubconf.py`` whose public callables
are the entrypoints; ``dependencies = [...]`` in hubconf is validated
before load. ``source='local'`` is fully supported; github/gitee need a
network fetch, unavailable in this environment (zero egress) — they
raise with the reference's repo-spec format so the call site is
portable.
"""
from __future__ import annotations

import importlib.util
import os
import sys

VAR_DEPENDENCY = "dependencies"
MODULE_HUBCONF = "hubconf.py"


def _import_hubconf(repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(repo_dir)
    return module


def _check_dependencies(m):
    deps = getattr(m, VAR_DEPENDENCY, None)
    if not deps:
        return
    missing = []
    for pkg in deps:
        try:
            __import__(pkg)
        except ImportError:
            missing.append(pkg)
    if missing:
        raise RuntimeError("Missing dependencies: " + ", ".join(missing))


def _resolve(repo_dir, source, force_reload):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed: "github" | "gitee" | '
            '"local".')
    if source != "local":
        raise RuntimeError(
            f"hub source={source!r} needs a network fetch of "
            f"{repo_dir!r} (repo_owner/repo_name[:tag]), which this "
            "environment cannot do (zero egress); clone the repo and use "
            "source='local'")
    return repo_dir


def list(repo_dir, source="github", force_reload=False):
    """Entrypoint names exported by the repo's hubconf (reference:
    hub.py:188)."""
    repo_dir = _resolve(repo_dir, source, force_reload)
    m = _import_hubconf(repo_dir)
    return [f for f in dir(m)
            if callable(getattr(m, f)) and not f.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    """The entrypoint's docstring (reference: hub.py:238)."""
    repo_dir = _resolve(repo_dir, source, force_reload)
    m = _import_hubconf(repo_dir)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Instantiate a hub entrypoint (reference: hub.py:286): validates
    ``dependencies``, resolves the callable, calls it with kwargs. The
    repo dir stays on sys.path for the call so entrypoints can lazily
    import sibling modules (the common hubconf layout)."""
    repo_dir = _resolve(repo_dir, source, force_reload)
    m = _import_hubconf(repo_dir)
    _check_dependencies(m)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    sys.path.insert(0, repo_dir)
    try:
        return fn(**kwargs)
    finally:
        if repo_dir in sys.path:
            sys.path.remove(repo_dir)


__all__ = ["list", "help", "load"]
