"""Model summary table (reference: python/paddle/hapi/model_summary.py).

Walks sublayers with forward hooks to record output shapes, then prints a
Keras-style table with trainable/total parameter counts.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import to_jax_dtype


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []        # (name, type, out_shape, n_params)
    hooks = []

    def make_hook(name, layer):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "-"
            n = int(sum(np.prod(p.shape) for p in layer._parameters.values()
                        if p is not None))
            rows.append((name, type(layer).__name__, shape, n))
        return hook

    for name, layer in net.named_sublayers():
        if not layer._sub_layers:  # leaves only, like the reference table
            hooks.append(layer.register_forward_post_hook(make_hook(name, layer)))

    was_training = net.training
    net.eval()
    try:
        if input is not None:
            xs = input if isinstance(input, (list, tuple)) else [input]
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = input_size if isinstance(input_size, list) and \
                isinstance(input_size[0], (list, tuple)) else [input_size]
            dts = dtypes if isinstance(dtypes, (list, tuple)) else \
                [dtypes] * len(sizes)
            xs = [Tensor(jnp.zeros([s if s is not None else 1 for s in size],
                                   to_jax_dtype(dt or "float32")))
                  for size, dt in zip(sizes, dts)]
        from ..core.autograd import no_grad
        with no_grad():
            net(*xs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(np.prod(p.shape) for p in net.parameters()
                        if not p.stop_gradient))
    w = max([len(r[0]) for r in rows] + [10])
    print("-" * (w + 45))
    print(f"{'Layer':<{w}} {'Type':<16} {'Output Shape':<18} {'Params':>8}")
    print("=" * (w + 45))
    for name, typ, shape, n in rows:
        print(f"{name:<{w}} {typ:<16} {str(shape):<18} {n:>8}")
    print("=" * (w + 45))
    print(f"Total params: {total}")
    print(f"Trainable params: {trainable}")
    print(f"Non-trainable params: {total - trainable}")
    return {"total_params": total, "trainable_params": trainable}


__all__ = ["summary"]
