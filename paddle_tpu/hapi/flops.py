"""paddle.flops — per-layer FLOP counting (reference:
python/paddle/hapi/dynamic_flops.py:40 flops / :237 dynamic_flops).

Forward-post hooks record each LEAF layer's FLOPs from its input/output
shapes; multiply-accumulate counts follow the reference's counters
(convNd: out_numel * cin/groups * prod(k); linear: in_f * out_f * rows;
bn/activations: numel). ``custom_ops`` maps Layer classes to
``fn(layer, inputs, output) -> flops`` overrides.
"""
from __future__ import annotations

import numpy as np

from .. import nn


def _numel(t):
    n = 1
    for s in t.shape:
        n *= int(s)
    return n


def _count_conv(m, inputs, output):
    """MACs for forward AND transpose convs (the transpose conv's cost is
    the same product over its per-output-element gather)."""
    kernel_numel = 1
    for k in (m._kernel_size if isinstance(m._kernel_size, (list, tuple))
              else [m._kernel_size]):
        kernel_numel *= int(k)
    cin = int(m._in_channels)
    groups = int(getattr(m, "_groups", 1) or 1)
    return _numel(output) * (cin // groups) * kernel_numel


def _count_linear(m, inputs, output):
    in_f = int(m.weight.shape[0])
    return _numel(output) * in_f


def _count_numel(m, inputs, output):
    return _numel(output)


def _count_zero(m, inputs, output):
    return 0


def _transpose_convs():
    return tuple(getattr(nn, n) for n in
                 ("Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose")
                 if hasattr(nn, n))


_COUNTERS = None


def _counters():
    global _COUNTERS
    if _COUNTERS is None:
        _COUNTERS = [
            ((nn.Conv1D, nn.Conv2D, nn.Conv3D) + _transpose_convs(),
             _count_conv),
            ((nn.Linear,), _count_linear),
            ((nn.BatchNorm1D, nn.BatchNorm2D, nn.BatchNorm3D, nn.BatchNorm,
              nn.LayerNorm, nn.GroupNorm, nn.InstanceNorm2D), _count_numel),
            ((nn.ReLU, nn.ReLU6, nn.GELU, nn.Sigmoid, nn.Tanh, nn.Softmax,
              nn.Silu, nn.LeakyReLU, nn.Hardswish, nn.Hardsigmoid),
             _count_numel),
            ((nn.AvgPool1D, nn.AvgPool2D, nn.AvgPool3D,
              nn.AdaptiveAvgPool1D, nn.AdaptiveAvgPool2D,
              nn.AdaptiveAvgPool3D), _count_numel),
            ((nn.MaxPool1D, nn.MaxPool2D, nn.MaxPool3D, nn.Dropout,
              nn.Flatten), _count_zero),
        ]
    return _COUNTERS


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total multiply-accumulate FLOPs of one forward at ``input_size``
    (reference: dynamic_flops.py flops). Returns an int; with
    ``print_detail`` also prints the per-layer table."""
    import paddle_tpu as paddle

    custom_ops = custom_ops or {}
    rows = []
    handles = []

    def make_hook(layer, counter):
        def hook(m, inputs, output):
            out = output[0] if isinstance(output, (list, tuple)) else output
            f = int(counter(m, inputs, out))
            params = sum(_numel(p) for p in m.parameters())
            rows.append((type(m).__name__, list(out.shape), params, f))
            return output
        return hook

    def resolve(layer):
        if type(layer) in custom_ops:
            return custom_ops[type(layer)]
        for classes, fn in _counters():
            if isinstance(layer, classes):
                return fn
        return None

    for layer in net.sublayers(include_self=True):
        if list(layer.children()):
            continue   # leaves only (sublayers() already deduplicates)
        counter = resolve(layer)
        if counter is None:
            if any(True for _ in layer.parameters()):
                import warnings
                warnings.warn(
                    f"paddle.flops: no counter for {type(layer).__name__}; "
                    "its FLOPs are not included (pass custom_ops)")
            continue
        handles.append(layer.register_forward_post_hook(
            make_hook(layer, counter)))

    # snapshot PER-LAYER training flags: net.train() would recursively
    # force training=True onto sublayers the user froze in eval mode
    modes = [(m, m.training) for m in net.sublayers(include_self=True)]
    net.eval()
    try:
        x = paddle.to_tensor(
            np.zeros(tuple(input_size), np.float32))
        net(x)
    finally:
        for h in handles:
            h.remove()
        for m, was in modes:
            m.training = was

    total = sum(r[3] for r in rows)
    if print_detail:
        print(f"{'Layer':<24}{'Output shape':<24}{'Params':>12}"
              f"{'FLOPs':>16}")
        for name, shape, params, f in rows:
            print(f"{name:<24}{str(shape):<24}{params:>12}{f:>16}")
        print(f"Total FLOPs: {total}")
    return total


__all__ = ["flops"]
