"""hapi — high-level Keras-style training API (analog of python/paddle/hapi/)."""
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
