"""hapi callbacks (reference: python/paddle/hapi/callbacks.py).

Hook points match the reference's Callback protocol: the Model drives
CallbackList through train/eval/predict; ProgBarLogger and ModelCheckpoint
are configured by default in Model.fit, mirroring callbacks.py
config_callbacks.
"""
from __future__ import annotations

import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Console progress/metrics logger (reference: callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        from ..core.async_scalar import AsyncScalar

        def one(k, v):
            if isinstance(v, AsyncScalar):
                # printing IS a sync boundary: resolve (Model.fit already
                # fetched the window at log_freq steps, so this is free)
                v = float(v)
            return f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"

        return " - ".join(one(k, v) for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {step}{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Periodic save (reference: callbacks.py ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class _MonitorMixin:
    """Shared metric-monitoring machinery (mode resolution, improvement
    test, metric extraction) for EarlyStopping / ReduceLROnPlateau."""

    def _init_monitor(self, monitor, mode, min_delta):
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def _best_init(self):
        return float("-inf") if self.mode == "max" else float("inf")

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def _metric(self, logs):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return None
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        return float(cur)


class EarlyStopping(_MonitorMixin, Callback):
    """Stop when a monitored metric stops improving
    (reference: callbacks.py EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self._init_monitor(monitor, mode, min_delta)
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.reset()

    def reset(self):
        self.wait = 0
        self.stopped_epoch = 0
        self.best = (self.baseline if self.baseline is not None
                     else self._best_init())

    def on_eval_end(self, logs=None):
        cur = self._metric(logs)
        if cur is None:
            return
        if self._better(float(cur)):
            self.best = float(cur)
            self.wait = 0
            if self.save_best_model and getattr(self.model, "save_dir", None):
                self.model.save(os.path.join(self.model.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: best {self.monitor} = {self.best:.5f}")


class LRScheduler(Callback):
    """Step the optimizer's LRScheduler (reference: callbacks.py LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [],
    })
    return cbk_list


class ReduceLROnPlateau(_MonitorMixin, Callback):
    """Scale the LR down when a monitored metric plateaus (reference:
    callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self._init_monitor(monitor, mode, min_delta)
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.reset()

    def reset(self):
        self.wait = 0
        self.cooldown_counter = 0
        self.best = self._best_init()

    def _scale_lr(self):
        """Multiply the live LR source by ``factor`` (bounded by min_lr).
        For a scheduler, scale BASE_LR so its own decay composes on the
        reduced base rather than double-applying (review: writing the
        decayed last_lr into base_lr compounds the reduction)."""
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return None, None
        lr = getattr(opt, "_learning_rate", None)
        if hasattr(lr, "step"):        # an LRScheduler object
            old = float(getattr(lr, "base_lr", getattr(lr, "last_lr", 0)))
            new = max(old * self.factor, self.min_lr)
            if hasattr(lr, "base_lr"):
                lr.base_lr = new
            if hasattr(lr, "last_lr"):
                lr.last_lr = max(float(lr.last_lr) * self.factor,
                                 self.min_lr)
            return old, new
        old = float(lr) if lr is not None else None
        if old is None or old <= self.min_lr:
            return old, old
        new = max(old * self.factor, self.min_lr)
        opt.set_lr(new)                # optimizer API (optimizer.py:44)
        return old, new

    def on_eval_end(self, logs=None):
        cur = self._metric(logs)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            # in cooldown: no plateau counting at all (upstream if/else)
            self.cooldown_counter -= 1
            self.wait = 0
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            old, new = self._scale_lr()
            if self.verbose and old is not None and new != old:
                print(f"ReduceLROnPlateau: lr {old:.6g} -> {new:.6g}")
            self.cooldown_counter = self.cooldown
            self.wait = 0


__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "ReduceLROnPlateau",
           "config_callbacks"]
