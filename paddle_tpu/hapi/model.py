"""hapi Model: Keras-style fit/evaluate/predict over a Layer.

TPU-native analog of the reference's high-level Model
(reference: python/paddle/hapi/model.py:1472 fit; evaluate/predict below
it; save/load; summary). The reference keeps dygraph/static dual paths;
here there is one path — eager train steps, with an optional fused
``paddle_tpu.jit.TrainStep`` when ``prepare(..., use_jit=True)`` — the
to_static role on this stack.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor
from ..core.autograd import no_grad
from ..core.async_scalar import AsyncScalar, fetch_all
from ..core.flags import GLOBAL_FLAGS
from ..io import DataLoader, Dataset
from ..metric import Metric


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_loader(data, batch_size, shuffle, num_workers, drop_last=False):
    if data is None or isinstance(data, DataLoader):
        return data
    return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                      num_workers=num_workers, drop_last=drop_last)


def _split_batch(batch, n_labels):
    batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
    if n_labels == 0:
        return batch, []
    return batch[:-n_labels], batch[-n_labels:]


class Model:
    """``Model(network)`` then ``prepare(optimizer, loss, metrics)`` then
    ``fit/evaluate/predict`` (reference: hapi/model.py:1472)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self.save_dir = None
        self._train_step = None

    # ---- setup ----
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, use_jit=False, accumulate_steps=1):
        # amp_configs (reference model.py:prepare): "O0"/"O1"/"O2" or a
        # dict with level/dtype/custom lists — train, eval, AND the
        # fused use_jit step all run their forwards under amp.auto_cast
        self._amp_kwargs = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                cfg = {"level": amp_configs}
            elif isinstance(amp_configs, dict):
                cfg = dict(amp_configs)
            else:
                raise TypeError(f"amp_configs must be a str level or "
                                f"dict, got {type(amp_configs)}")
            allowed = {"level", "dtype", "custom_white_list",
                       "custom_black_list", "use_promote"}
            unknown = set(cfg) - allowed
            if unknown:
                raise ValueError(f"unknown amp_configs keys {unknown}")
            level = cfg.get("level", "O1")
            if level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp level must be 'O0'/'O1'/'O2', got {level!r}")
            if level != "O0":
                cfg["level"] = level
                cfg.setdefault("dtype", "bfloat16")
                self._amp_kwargs = cfg
        self._optimizer = optimizer
        self._loss = loss
        metrics = _to_list(metrics)
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric.Metric")
        self._metrics = metrics
        self._use_jit = use_jit
        # micro-batch gradient accumulation inside the compiled step
        # (jit.TrainStep(accumulate_steps=K)): each train batch splits
        # into K micro-batches and one optimizer update applies the mean
        # grads — K× effective batch at batch/K activation memory.
        # Requires use_jit; the eager path raises to avoid silently
        # training with a different effective batch than asked.
        self._accumulate_steps = int(accumulate_steps)
        if self._accumulate_steps < 1:
            raise ValueError("accumulate_steps must be >= 1")
        if self._accumulate_steps > 1 and not use_jit:
            raise ValueError(
                "prepare(accumulate_steps>1) requires use_jit=True — "
                "gradient accumulation runs inside the compiled TrainStep")
        self._train_step = None
        return self

    # ---- single-batch ops (reference: model.py train_batch/eval_batch) ----
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = [self._tensorize(x) for x in _to_list(inputs)]
        labels = [self._tensorize(y) for y in _to_list(labels)]
        if self._use_jit and self._train_step is None:
            from ..jit import TrainStep
            n_in = len(inputs)

            def loss_fn(*flat):
                with self._amp_ctx():
                    outs = self.network(*flat[:n_in])
                    return self._compute_loss(outs, list(flat[n_in:]))

            self._train_step = TrainStep(
                self.network, loss_fn, self._optimizer,
                accumulate_steps=getattr(self, "_accumulate_steps", 1))
        if self._train_step is not None:
            loss = self._train_step(*inputs, *labels)
            outputs = None  # fused step doesn't surface intermediate outputs
        else:
            with self._amp_ctx():
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels)
            loss.backward()
            if update and self._optimizer is not None:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return (self._loss_out(loss), metrics)

    def _loss_out(self, loss):
        """Deferred loss: the dispatched step's device scalar rides back as
        an AsyncScalar whose ``float()`` is the only sync point, so the
        device never idles for a number the host prints every ``log_freq``
        steps. ``FLAGS_async_pipeline=False`` restores the per-step
        blocking fetch (bit-identical values)."""
        if GLOBAL_FLAGS.get("async_pipeline"):
            return AsyncScalar(loss)
        return float(np.asarray(loss.numpy()))

    def _amp_ctx(self):
        from contextlib import nullcontext
        kw = getattr(self, "_amp_kwargs", None)
        if not kw:
            return nullcontext()
        from ..amp import auto_cast
        return auto_cast(**kw)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        with no_grad():
            inputs = [self._tensorize(x) for x in _to_list(inputs)]
            labels = [self._tensorize(y) for y in _to_list(labels)]
            with self._amp_ctx():
                outputs = self.network(*inputs)
                loss = self._compute_loss(outputs, labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        return (self._loss_out(loss) if loss is not None else None, metrics)

    def predict_batch(self, inputs):
        self.network.eval()
        with no_grad():
            inputs = [self._tensorize(x) for x in _to_list(inputs)]
            out = self.network(*inputs)
        return [o.numpy() for o in _to_list(out)]

    # ---- loops ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            checkpoint_dir=None, checkpoint_freq=None,
            keep_last_checkpoints=3, resume=False):
        """Train; with ``checkpoint_dir`` set, the FULL training state
        (params, optimizer state incl. fused flat buckets, the global
        RNG stream, and the data-loader cursor) is saved through the
        crash-consistent :class:`~paddle_tpu.io.persist.ArtifactStore`
        every ``checkpoint_freq`` optimizer steps (default: once per
        epoch). ``resume=True`` restores the newest verified checkpoint
        and continues from the exact step boundary it captured — the
        resumed loss trajectory is bit-identical to the unkilled run's
        PROVIDED the loader shuffles with a SEEDED sampler (each
        epoch's batch order is then pinned by ``set_epoch`` to a pure
        function of (sampler seed, epoch); an unseeded shuffle draws
        off numpy's global RNG, is not resumable, and warns). A corrupt
        newest version falls back to the last good one; no checkpoint
        at all is a clean cold start."""
        from .callbacks import config_callbacks
        loader = _as_loader(train_data, batch_size, shuffle, num_workers,
                            drop_last)
        eval_loader = _as_loader(eval_data, batch_size, False, num_workers)
        self.save_dir = save_dir
        self.stop_training = False
        steps = len(loader) if hasattr(loader, "__len__") else None
        ckpt_store = None
        cursor = {"epoch": 0, "step_in_epoch": 0, "global_step": 0}
        if checkpoint_dir is not None:
            from ..io.persist import (ArtifactStore, capture_training_state,
                                      restore_training_state)
            ckpt_store = ArtifactStore(checkpoint_dir,
                                       keep_last=keep_last_checkpoints)
            # resumable shuffling precondition: an UNSEEDED random
            # sampler permutes off numpy's global RNG, which the
            # checkpoint does not (and cannot portably) capture — a
            # resumed epoch would fast-forward over a DIFFERENT batch
            # order, training some samples twice and others never.
            # Warn now, at save time, not at the resume that corrupts.
            smp = getattr(getattr(loader, "batch_sampler", None),
                          "sampler", None)
            if smp is not None and hasattr(smp, "set_epoch") \
                    and getattr(smp, "generator", None) is None:
                import warnings
                warnings.warn(
                    "fit(checkpoint_dir=...): the train loader shuffles "
                    "with an UNSEEDED sampler, so a resumed run cannot "
                    "replay the same batch order (bit-identical resume "
                    "is lost). Pass a seeded sampler, e.g. DataLoader("
                    "ds, batch_sampler=BatchSampler(sampler=RandomSampler"
                    "(ds, generator=SEED), batch_size=...)).",
                    stacklevel=2)
            if resume:
                res = ckpt_store.load("train_state")
                if res is not None:
                    cursor.update(restore_training_state(
                        res, model=self, optimizer=self._optimizer,
                        scaler=getattr(self, "_scaler", None)))

            def _save_ckpt(epoch, step_in_epoch):
                arrays, meta = capture_training_state(
                    model=self, optimizer=self._optimizer,
                    scaler=getattr(self, "_scaler", None),
                    cursor={"epoch": epoch,
                            "step_in_epoch": step_in_epoch,
                            "global_step": cursor["global_step"]})
                ckpt_store.save("train_state", arrays, meta)
        elif resume:
            raise ValueError("fit(resume=True) needs checkpoint_dir")
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metric_names())
        cbks.on_train_begin()
        history = []
        start_epoch = int(cursor["epoch"])
        skip_steps = int(cursor["step_in_epoch"])
        if steps is not None and skip_steps >= steps:
            # the checkpoint landed exactly on an epoch boundary:
            # resume at the NEXT epoch's first batch
            start_epoch += 1
            skip_steps = 0
        for epoch in range(start_epoch, epochs):
            cbks.on_epoch_begin(epoch)
            step_hook = None
            if ckpt_store is not None:
                freq = checkpoint_freq if checkpoint_freq else \
                    (steps if steps else 1)

                def step_hook(step, epoch=epoch, freq=freq):
                    cursor["global_step"] += 1
                    if cursor["global_step"] % max(int(freq), 1) == 0:
                        _save_ckpt(epoch, step + 1)
            # epoch pinning is scoped to CHECKPOINTED runs: they need
            # epoch e's batch order to be a pure function of (sampler
            # seed, e). Plain fit() keeps the legacy self-advancing
            # sampler behavior (repeated fit() calls on one loader keep
            # drawing fresh permutations).
            logs = self._run_one_epoch(loader, cbks, "train", log_freq,
                                       epoch=epoch
                                       if ckpt_store is not None else None,
                                       skip_steps=skip_steps
                                       if epoch == start_epoch else 0,
                                       step_hook=step_hook)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                cbks.on_eval_begin()
                eval_logs = self._run_one_epoch(eval_loader, cbks, "eval",
                                                log_freq)
                cbks.on_eval_end(eval_logs)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            history.append(logs)
            if self.stop_training:
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        from .callbacks import config_callbacks
        loader = _as_loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, epochs=None,
                                steps=len(loader) if hasattr(loader, "__len__") else None,
                                log_freq=log_freq, verbose=verbose,
                                metrics=self._metric_names(), mode="eval")
        cbks.on_eval_begin()
        logs = self._run_one_epoch(loader, cbks, "eval", log_freq)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from .callbacks import config_callbacks
        loader = _as_loader(test_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=None,
                                steps=steps, verbose=verbose,
                                metrics=[], mode="predict")
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            inputs, _ = _split_batch(batch, 0)
            outputs.append(self.predict_batch(inputs))
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        if not outputs:
            return []
        n_out = len(outputs[0])
        per_output = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            per_output = [np.concatenate(o, axis=0) for o in per_output]
        return per_output

    # ---- persistence (reference: model.py save/load) ----
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..framework import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import load as fload
        state = fload(path + ".pdparams")
        if skip_mismatch:
            # reference semantics: drop entries whose name or shape does
            # not match the network instead of raising
            own = dict(self.network.state_dict())
            state = {k: v for k, v in state.items()
                     if k in own and tuple(np.asarray(v).shape)
                     == tuple(own[k].shape)}
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path) and hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

    # ---- internals ----
    def _tensorize(self, x):
        # Tensor() already normalizes host data (np.asarray + dtype
        # defaulting); the extra np.asarray wrapper forced an eager host
        # copy for list inputs before Tensor staged them again
        return x if isinstance(x, Tensor) else Tensor(x)

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            out = _to_list(outputs)[0]
            return out.mean()
        outs = _to_list(outputs)
        return self._loss(*(outs + labels))

    def _update_metrics(self, outputs, labels):
        res = {}
        if outputs is None:
            return res
        outs = _to_list(outputs)
        for m in self._metrics:
            inp = m.compute(*(outs + labels))
            m.update(*[np.asarray(i.numpy() if isinstance(i, Tensor) else i)
                       for i in _to_list(inp)])
            res[m.name() if not isinstance(m.name(), list) else m.name()[0]] = \
                m.accumulate()
        return res

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names += n if isinstance(n, list) else [n]
        return names

    def _run_one_epoch(self, loader, cbks, mode, log_freq=10, epoch=None,
                       skip_steps=0, step_hook=None):
        from ..io.prefetch import PIPELINE_METRICS as _pm
        if mode == "train" and epoch is not None:
            # pin the epoch's shuffle seed: epoch e draws the same batch
            # sequence whether this is the first process to serve it or
            # a killed-and-resumed one (samplers expose set_epoch;
            # loaders without one keep their legacy self-advancing seed)
            bs = getattr(loader, "batch_sampler", None)
            if bs is not None and hasattr(bs, "set_epoch"):
                bs.set_epoch(epoch)
        for m in self._metrics:
            m.reset()
        losses = []
        pending = []   # dispatched-but-unfetched AsyncScalar losses
        window = max(1, int(GLOBAL_FLAGS.get("async_inflight_steps")))
        # fetch cadence = min(log_freq, window), via exactly ONE trigger:
        # log_freq boundaries when they are at least as frequent as the
        # window (aligned with ProgBarLogger prints), else the window
        # alone — running both would interleave phases (fetches at 0, 8,
        # 10, 18, 20, ... for log_freq=10/K=8) and break the
        # steps/min(log_freq, K) + 2 sync bound the gate enforces
        boundary_mode = bool(log_freq) and log_freq <= window
        logs = {}
        for step, batch in enumerate(loader):
            if step < skip_steps:
                # resume fast-forward: these batches were trained before
                # the kill — consume them (the sampler order must stay
                # identical) without training, callbacks, or logging
                continue
            inputs, labels = _split_batch(batch, max(1, len(self._labels))
                                          if (self._loss is not None) else 0)
            if mode == "train":
                cbks.on_train_batch_begin(step)
                loss, metrics = self.train_batch(inputs, labels)
                if step_hook is not None:
                    step_hook(step)
            else:
                cbks.on_eval_batch_begin(step)
                loss, metrics = self.eval_batch(inputs, labels)
            if loss is not None:
                losses.append(loss)
            if isinstance(loss, AsyncScalar) and not loss.resolved:
                # bounded in-flight window: the host may run up to
                # ``window`` dispatched steps ahead, fetching the whole
                # window in ONE blocking device_get per cadence point
                pending.append(loss)
                _pm.set_in_flight(len(pending))
                if (step % log_freq == 0) if boundary_mode \
                        else (len(pending) >= window):
                    fetch_all(pending)
                    pending.clear()
                    _pm.set_in_flight(0)
            logs = {"loss": float(loss)
                    if isinstance(loss, AsyncScalar) and loss.resolved
                    else loss, **metrics}
            if mode == "train":
                cbks.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            else:
                cbks.on_eval_batch_end(step, logs)
        if pending:
            fetch_all(pending)
            pending.clear()
            _pm.set_in_flight(0)
        if losses:
            logs["loss"] = float(np.mean([float(l) for l in losses]))
        return logs


__all__ = ["Model"]
