"""Workload specification -> reproducible timed request traces.

A :class:`WorkloadSpec` describes production-shaped serving traffic as a
small set of distribution knobs — arrival process (Poisson or
deterministic), prompt/output length ranges, a shared-prefix cohort mix
(the Ragged Paged Attention traffic the prefix cache exists for), and
per-request SLOs — and ``compile()``\\ s it into a concrete list of
:class:`TraceRequest`\\ s with explicit virtual arrival times and token
ids.

Everything is derived from ONE ``numpy`` Generator seeded by
``spec.seed``: the same spec compiles to the same trace, byte for byte,
on every run (``trace_fingerprint`` is the gate's witness —
tests/test_loadgen.py). The trace is data, not behavior: the driver
(loadgen/driver.py) replays it against an ``LLMEngine`` on a virtual
clock, so the whole pipeline spec -> trace -> outcomes -> report is
deterministic and wall-clock-free.

Two SLOs per request, deliberately distinct:
- ``deadline_s`` is the QUEUE-WAIT shed SLO handed to the engine: a
  request still waiting this long after submission is load-shed
  (serving/scheduler.py ``shed_expired``);
- ``slo_e2e_s`` is the REPORT-side goodput bar: a finished request only
  counts as goodput if its end-to-end latency beat it. The engine never
  sees it — late completions still finish, they just don't score.
"""
from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, asdict

import numpy as np

#: arrival processes: the classic pair plus two time-varying shapes a
#: million-user front door actually sees — ``diurnal`` (sinusoidal rate
#: modulation, the day/night cycle compressed onto the virtual clock)
#: and ``flash_crowd`` (a multiplicative rate spike over a window — the
#: thundering herd the degradation ladder exists for)
ARRIVALS = ("poisson", "deterministic", "diurnal", "flash_crowd")

#: scenario lanes (ROADMAP item 5d): ``interactive`` is the classic
#: latency-scored lane; ``offline_batch`` is the throughput lane — no
#: queue-wait shed SLO by construction (``deadline_s`` must be None: a
#: batch job is never load-shed for waiting) and the report gains a
#: ``batch tokens/s`` section instead of scoring latency percentiles
LANES = ("interactive", "offline_batch")

#: hard ceiling of the long-context lane's prompt lengths — the 128k
#: target context ROADMAP 5(a)/(d) sizes the two-tier KV cache for
LONG_CONTEXT_CEILING = 131072


@dataclass(frozen=True)
class TraceRequest:
    """One concrete request of a compiled trace."""
    request_id: str
    arrival_s: float
    prompt_token_ids: tuple
    max_new_tokens: int
    deadline_s: float | None = None
    slo_e2e_s: float | None = None
    #: mid-flight abort SLO (serving/scheduler.py ``abort_expired``): a
    #: request still unfinished this long after submission is aborted at
    #: a step boundary (reason "deadline_exceeded") — unlike
    #: ``deadline_s`` it applies to RUNNING requests too
    abort_after_s: float | None = None
    temperature: float = 0.0
    #: per-request sampling knobs (serving/engine.py): 0 / 1.0 = off;
    #: seed None lets the engine derive one from the request_id
    top_k: int = 0
    top_p: float = 1.0
    seed: int | None = None
    eos_token_id: int | None = None
    #: cohort index when the prompt starts with a shared prefix, else -1
    prefix_cohort: int = -1
    #: owning tenant when the spec declares a tenant mix, else None —
    #: classic (no-tenant) traces never carry (or hash) the field
    tenant_id: str | None = None


@dataclass(frozen=True)
class WorkloadSpec:
    """Seeded description of a serving workload (docs/BENCH.md schema).

    ``prompt_len`` / ``output_len`` are inclusive uniform integer ranges.
    A ``shared_prefix_fraction`` > 0 routes that fraction of requests
    through one of ``num_shared_prefixes`` fixed token prefixes of length
    ``shared_prefix_len`` (prompt = cohort prefix + random tail), so the
    engine's prefix cache and CoW page sharing see realistic repeated
    system prompts instead of uniformly random tokens.
    """
    num_requests: int = 64
    seed: int = 0
    arrival: str = "poisson"        # ARRIVALS
    arrival_rate: float = 50.0      # requests per virtual second
    prompt_len: tuple = (4, 24)
    output_len: tuple = (2, 12)
    shared_prefix_fraction: float = 0.0
    shared_prefix_len: int = 0
    num_shared_prefixes: int = 1
    deadline_s: float | None = None
    slo_e2e_s: float | None = None
    abort_after_s: float | None = None
    #: time-varying arrival-shape knobs. ``diurnal``: the instantaneous
    #: rate is ``arrival_rate * (1 + rate_amplitude * sin(2*pi*t /
    #: rate_period_s))``. ``flash_crowd``: the rate is multiplied by
    #: ``flash_multiplier`` inside the window ``[flash_at_s, flash_at_s
    #: + flash_duration_s)``. Ignored (and draw-free) for the classic
    #: arrivals, so pre-existing traces byte-persist.
    rate_period_s: float = 10.0
    rate_amplitude: float = 0.5
    flash_at_s: float = 1.0
    flash_duration_s: float = 1.0
    flash_multiplier: float = 8.0
    temperature: float = 0.0
    #: per-request sampling-knob ranges (inclusive): each request draws
    #: its own top_k from ``top_k`` ((0, 0) = off), its own top_p
    #: uniformly from ``top_p`` ((1.0, 1.0) = off), and its own PRNG
    #: seed from ``per_request_seed`` (None = engine-derived from the
    #: request_id). All ride the one spec rng stream, so they are part
    #: of the trace fingerprint.
    top_k: tuple = (0, 0)
    top_p: tuple = (1.0, 1.0)
    per_request_seed: tuple | None = None
    eos_token_id: int | None = None
    vocab_size: int = 128
    #: scenario lane (LANES): ``offline_batch`` forbids the queue-wait
    #: shed SLO (throughput, not latency — the report scores batch
    #: tokens/s) and is otherwise draw-free, so classic traces
    #: byte-persist
    lane: str = "interactive"
    #: long-context lane (ROADMAP 5d, partial): this fraction of
    #: requests draws its prompt length from ``long_context_len``
    #: (inclusive range, capped at LONG_CONTEXT_CEILING = 128k tokens)
    #: instead of ``prompt_len`` — the chunked-prefill-friendly
    #: long-document traffic the two-tier KV cache exists for. Long
    #: requests never join a shared-prefix cohort. 0.0 (the default)
    #: consumes no rng draws: pre-existing trace fingerprints
    #: byte-persist.
    long_context_fraction: float = 0.0
    long_context_len: tuple | None = None
    #: multi-tenant mix (tenancy/policy.py): each entry is a mapping
    #: ``{"tenant_id": str, "weight": float > 0,
    #: "quota_tokens_per_s": float | None, "adapter_id": Any,
    #: "abusive": bool}`` — requests draw their owner from the weighted
    #: mix (ONE extra rng draw per request, at the END of the
    #: per-request draw order, so every pre-tenant trace byte-persists).
    #: At most one tenant may be ``abusive``: its SELECTION share is
    #: multiplied by ``abusive_multiplier`` — the seeded noisy-neighbor
    #: flood — while its declared ``weight``/quota (what the engine's
    #: fair scheduler sees) stays honest.
    tenants: tuple = ()
    abusive_multiplier: float = 8.0

    def __post_init__(self):
        if self.tenants:
            object.__setattr__(
                self, "tenants", tuple(dict(t) for t in self.tenants))
            allowed = {"tenant_id", "weight", "quota_tokens_per_s",
                       "adapter_id", "abusive"}
            seen = set()
            n_abusive = 0
            for t in self.tenants:
                unknown = set(t) - allowed
                if unknown:
                    raise ValueError(
                        f"unknown tenant keys {sorted(unknown)}; "
                        f"allowed: {sorted(allowed)}")
                tid = t.get("tenant_id")
                if not isinstance(tid, str) or not tid:
                    raise ValueError(
                        f"each tenant needs a non-empty string "
                        f"tenant_id, got {tid!r}")
                if tid in seen:
                    raise ValueError(f"duplicate tenant_id {tid!r}")
                seen.add(tid)
                w = float(t.get("weight", 1.0))
                if w <= 0:
                    raise ValueError(
                        f"tenant {tid!r}: weight must be > 0, got {w}")
                q = t.get("quota_tokens_per_s")
                if q is not None and float(q) <= 0:
                    raise ValueError(
                        f"tenant {tid!r}: quota_tokens_per_s must be "
                        f"> 0 (or None), got {q}")
                n_abusive += bool(t.get("abusive", False))
            if n_abusive > 1:
                raise ValueError(
                    "at most one tenant may be abusive (the "
                    "noisy-neighbor scenario has ONE noisy neighbor)")
            if self.abusive_multiplier < 1.0:
                raise ValueError(
                    f"abusive_multiplier must be >= 1, "
                    f"got {self.abusive_multiplier}")
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.arrival not in ARRIVALS:
            raise ValueError(f"arrival must be one of {ARRIVALS}, "
                             f"got {self.arrival!r}")
        if self.arrival_rate <= 0:
            raise ValueError("arrival_rate must be > 0")
        for name in ("prompt_len", "output_len"):
            lo, hi = getattr(self, name)
            if not (1 <= lo <= hi):
                raise ValueError(f"{name} must be an inclusive range "
                                 f"1 <= lo <= hi, got {(lo, hi)}")
        if not 0.0 <= self.shared_prefix_fraction <= 1.0:
            raise ValueError("shared_prefix_fraction must be in [0, 1]")
        if self.shared_prefix_fraction > 0:
            if self.shared_prefix_len < 1:
                raise ValueError("shared_prefix_len must be >= 1 when a "
                                 "shared-prefix cohort is requested")
            if self.shared_prefix_len >= self.prompt_len[1]:
                # cohort prompts are prefix + >=1 fresh tail token; a
                # prefix at/above the declared max would silently emit
                # prompts past prompt_len[1] (and past any engine sized
                # for it — mass rejected_oversize with nothing pointing
                # at the spec)
                raise ValueError(
                    f"shared_prefix_len {self.shared_prefix_len} must be "
                    f"< prompt_len hi {self.prompt_len[1]} (cohort "
                    f"prompts = prefix + at least one fresh token)")
            if self.num_shared_prefixes < 1:
                raise ValueError("num_shared_prefixes must be >= 1")
        if self.vocab_size < 2:
            raise ValueError("vocab_size must be >= 2")
        if self.arrival == "diurnal":
            if self.rate_period_s <= 0:
                raise ValueError("rate_period_s must be > 0")
            if not 0.0 <= self.rate_amplitude < 1.0:
                raise ValueError(
                    f"rate_amplitude must be in [0, 1) (the instantaneous "
                    f"rate must stay positive), got {self.rate_amplitude}")
        if self.arrival == "flash_crowd":
            if self.flash_at_s < 0 or self.flash_duration_s <= 0:
                raise ValueError("flash window must satisfy flash_at_s "
                                 ">= 0 and flash_duration_s > 0")
            if self.flash_multiplier < 1.0:
                raise ValueError(
                    f"flash_multiplier must be >= 1, "
                    f"got {self.flash_multiplier}")
        if self.abort_after_s is not None and self.abort_after_s <= 0:
            raise ValueError("abort_after_s must be > 0 (or None)")
        klo, khi = self.top_k
        if not 0 <= klo <= khi:
            raise ValueError(f"top_k must be an inclusive range "
                             f"0 <= lo <= hi, got {self.top_k}")
        plo, phi = self.top_p
        if not 0.0 < plo <= phi <= 1.0:
            raise ValueError(f"top_p must be an inclusive range in "
                             f"(0, 1], got {self.top_p}")
        if self.per_request_seed is not None:
            slo, shi = self.per_request_seed
            if not 0 <= slo <= shi:
                raise ValueError(
                    f"per_request_seed must be an inclusive range "
                    f"0 <= lo <= hi, got {self.per_request_seed}")
        if self.lane not in LANES:
            raise ValueError(f"lane must be one of {LANES}, "
                             f"got {self.lane!r}")
        if self.lane == "offline_batch" and self.deadline_s is not None:
            # a batch job waits as long as it waits: shedding it for
            # queue age would silently convert offered throughput work
            # into losses nobody asked to score
            raise ValueError(
                "offline_batch lane forbids deadline_s (throughput, "
                "not latency — batch work is never queue-shed)")
        if not 0.0 <= self.long_context_fraction <= 1.0:
            raise ValueError(
                "long_context_fraction must be in [0, 1]")
        if self.long_context_fraction > 0:
            if self.long_context_len is None:
                raise ValueError(
                    "long_context_len is required when "
                    "long_context_fraction > 0")
            llo, lhi = self.long_context_len
            if not 1 <= llo <= lhi:
                raise ValueError(
                    f"long_context_len must be an inclusive range "
                    f"1 <= lo <= hi, got {self.long_context_len}")
            if lhi > LONG_CONTEXT_CEILING:
                raise ValueError(
                    f"long_context_len hi {lhi} exceeds the "
                    f"{LONG_CONTEXT_CEILING}-token ceiling")

    def describe(self) -> dict:
        """Plain-dict view of the spec for the report artifact."""
        return asdict(self)

    def tenant_specs(self) -> list:
        """Engine-side ``TenantSpec`` kwargs: the declared entitlements
        minus the loadgen-only ``abusive`` flag — the flood is a TRAFFIC
        shape; the scheduler sees only the honest weight/quota."""
        return [{k: v for k, v in t.items() if k != "abusive"}
                for t in self.tenants]

    def compile(self) -> list:
        """Materialize the trace: one rng stream, stable ids, sorted
        non-decreasing arrival times."""
        rng = np.random.default_rng(self.seed)
        prefixes = []
        if self.shared_prefix_fraction > 0:
            prefixes = [tuple(int(t) for t in rng.integers(
                0, self.vocab_size, (self.shared_prefix_len,)))
                for _ in range(self.num_shared_prefixes)]
        plo, phi = self.prompt_len
        olo, ohi = self.output_len
        # tenant selection shares: the abusive tenant floods by
        # multiplied SHARE (it sends more traffic), not by multiplied
        # scheduler weight (its declared weight stays honest)
        tenant_cum = None
        if self.tenants:
            shares = [float(t.get("weight", 1.0))
                      * (self.abusive_multiplier
                         if t.get("abusive", False) else 1.0)
                      for t in self.tenants]
            total = sum(shares)
            acc, tenant_cum = 0.0, []
            for s in shares:
                acc += s / total
                tenant_cum.append(acc)
        t = 0.0
        trace = []
        for i in range(self.num_requests):
            if self.arrival == "deterministic":
                t = i / self.arrival_rate
            else:
                # Poisson family: the instantaneous rate may vary with
                # the CURRENT time (local-rate approximation of an
                # inhomogeneous process — deterministic given the seed).
                # Plain "poisson" draws exactly what it always drew, so
                # pre-existing trace fingerprints are unchanged.
                rate = self.arrival_rate
                if self.arrival == "diurnal":
                    rate *= 1.0 + self.rate_amplitude * math.sin(
                        2.0 * math.pi * t / self.rate_period_s)
                elif self.arrival == "flash_crowd":
                    if self.flash_at_s <= t \
                            < self.flash_at_s + self.flash_duration_s:
                        rate *= self.flash_multiplier
                t += float(rng.exponential(1.0 / max(rate, 1e-9)))
            # long-context lane: draw-free at fraction 0, so classic
            # traces (and their fingerprints) byte-persist; a long
            # request replaces its prompt-length draw and never joins a
            # shared-prefix cohort (a 100k-token document is not a
            # repeated system prompt)
            is_long = self.long_context_fraction > 0 and \
                float(rng.random()) < self.long_context_fraction
            if is_long:
                llo, lhi = self.long_context_len
                plen = int(rng.integers(llo, lhi + 1))
            else:
                plen = int(rng.integers(plo, phi + 1))
            olen = int(rng.integers(olo, ohi + 1))
            cohort = -1
            if prefixes and not is_long and float(rng.random()) \
                    < self.shared_prefix_fraction:
                cohort = int(rng.integers(0, self.num_shared_prefixes))
                # at least one fresh tail token: the last prompt token is
                # never shareable anyway (its logits seed generation)
                tail = max(plen - self.shared_prefix_len, 1)
                prompt = prefixes[cohort] + tuple(int(x) for x in
                                                  rng.integers(
                    0, self.vocab_size, (tail,)))
            else:
                prompt = tuple(int(x) for x in rng.integers(
                    0, self.vocab_size, (plen,)))
            # per-request sampling knobs: degenerate ranges take the
            # fixed value WITHOUT consuming rng draws, so a spec that
            # leaves them at the defaults compiles to the same
            # prompts/arrivals/lengths it did before the knobs existed
            # (the fingerprint itself is schema-versioned by whatever
            # fields it hashes — it changed when the knobs were added)
            klo, khi = self.top_k
            tk = klo if klo == khi else int(rng.integers(klo, khi + 1))
            plo_, phi_ = self.top_p
            tp = plo_ if plo_ == phi_ else float(rng.uniform(plo_, phi_))
            seed = None
            if self.per_request_seed is not None:
                slo, shi = self.per_request_seed
                seed = slo if slo == shi else int(
                    rng.integers(slo, shi + 1))
            # tenant owner: LAST per-request draw, and only when a mix
            # is declared — classic traces consume exactly the draws
            # they always did, so their fingerprints byte-persist
            tenant_id = None
            if tenant_cum is not None:
                u = float(rng.random())
                for j, edge in enumerate(tenant_cum):
                    if u < edge or j == len(tenant_cum) - 1:
                        tenant_id = self.tenants[j]["tenant_id"]
                        break
            trace.append(TraceRequest(
                request_id=f"lg-{self.seed}-{i}", arrival_s=t,
                prompt_token_ids=prompt, max_new_tokens=olen,
                deadline_s=self.deadline_s, slo_e2e_s=self.slo_e2e_s,
                abort_after_s=self.abort_after_s,
                temperature=self.temperature, top_k=tk, top_p=tp,
                seed=seed, eos_token_id=self.eos_token_id,
                prefix_cohort=cohort, tenant_id=tenant_id))
        return trace


def trace_fingerprint(trace) -> str:
    """Stable sha256 over the trace's full content — the determinism
    gate's witness: same spec => same fingerprint, across processes."""
    def row(r):
        out = [r.request_id, repr(r.arrival_s), list(r.prompt_token_ids),
               r.max_new_tokens, r.deadline_s, r.slo_e2e_s, r.temperature,
               r.top_k, repr(r.top_p), r.seed,
               r.eos_token_id, r.prefix_cohort,
               getattr(r, "abort_after_s", None)]
        # tenant owner hashes ONLY when set: classic traces keep their
        # pre-tenancy fingerprints byte for byte
        tid = getattr(r, "tenant_id", None)
        if tid is not None:
            out.append(tid)
        return out

    blob = json.dumps([row(r) for r in trace], sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


__all__ = ["ARRIVALS", "LANES", "LONG_CONTEXT_CEILING", "TraceRequest",
           "WorkloadSpec", "trace_fingerprint"]
