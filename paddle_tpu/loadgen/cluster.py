"""Cluster load driver: the whole fleet on ONE virtual clock.

The single-engine :class:`~paddle_tpu.loadgen.driver.Driver` replays a
trace against one ``LLMEngine``; this module does the same against a
:class:`~paddle_tpu.serving.cluster.ClusterEngine` — N replicas, the
router, the fault schedule, and every replica's degradation ladder all
advance on the one clock the driver owns, so fleet-level p50/p99,
goodput, retry counts, and time-in-degraded-state are deterministic
functions of (trace seed, engine seed, fault script): the same run
reproduces byte for byte, chip-free.

Differences from the single-engine driver, all deliberate:

- **Session affinity from cohorts** — a trace request in shared-prefix
  cohort ``c`` is submitted with ``session_id="cohort-c"``, so the
  router keeps a cohort's traffic on one replica's warm prefix cache
  (exactly what a production session router does with sticky keys).
- **Idle jumps stop at fault times** — an idle cluster fast-forwards to
  the next arrival OR the next scheduled fault, whichever is first: a
  crash scheduled into an idle gap still fires (and recovers) on time.
- **Every live pool is audited** — ``check_invariants()`` runs per
  replica per step; ``invariant_checks`` counts pool-audits, so a
  3-replica run proves 3x the audits of a single-engine run.
- **Retries are recorded per request** — ``RequestRecord.num_retries``
  comes from the cluster's requeue bookkeeping at the end of the run.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from ..serving.engine import RequestRejected
from .driver import Driver, VirtualClock, build_trace_records


@dataclass
class ClusterRunResult:
    """Everything one cluster load run observed, ready for
    :func:`~paddle_tpu.loadgen.report.build_cluster_report`."""
    records: list                      # [RequestRecord] in trace order
    duration_s: float = 0.0
    steps: int = 0
    step_time_s: float = 0.0
    #: fleet peaks: queued = parked at the router + waiting across
    #: replicas; running summed across replicas
    peak_queue_depth: int = 0
    peak_running: int = 0
    peak_parked: int = 0
    #: replica id -> peak page utilization observed on its pool(s) —
    #: replicas that crash and return get ONE lifetime peak
    per_replica_peak_utilization: dict = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)   # cluster snapshot at end
    #: pool audits that RAN and passed (every live replica, every
    #: ``check_every`` steps); 0 = auditing disabled, nothing proven
    invariant_checks: int = 0
    #: the cluster's RequestTracer when one was attached (shared by
    #: every replica, so a request's spans follow it across crashes and
    #: retry hops); None otherwise
    tracer: object = None
    #: the telemetry Scraper when one drove the run; None otherwise
    telemetry: object = None
    #: autoscale applications the driver made (scale_to calls whose
    #: target differed from the provisioned count)
    scale_events: int = 0
    #: decode-progress assertions that RAN and passed (disaggregated
    #: runs with ``check_decode_progress=True``: every caught-up row on
    #: a full-speed decode replica must gain a token every step — the
    #: "a 32k prompt never starves decode" gate, proof-by-survival like
    #: the pool audits); 0 = the check was off or never applicable
    decode_progress_checks: int = 0

    def by_status(self) -> dict:
        out: dict[str, int] = {}
        for r in self.records:
            out[r.status] = out.get(r.status, 0) + 1
        return out


class ClusterDriver:
    """Replays a compiled trace against a ``ClusterEngine`` whose
    ``now_fn`` is this driver's clock (mismatched clocks are refused,
    same contract as the single-engine driver)."""

    def __init__(self, cluster, clock: VirtualClock, *, step_time_s=0.01,
                 max_steps=200_000, check_invariants=True, check_every=1,
                 scraper=None, autoscale=False,
                 check_decode_progress=False):
        if step_time_s <= 0:
            raise ValueError("step_time_s must be > 0")
        if cluster._now != clock.now:
            raise ValueError(
                "cluster.now_fn is not this driver's clock — construct "
                "the ClusterEngine with now_fn=clock.now so faults, "
                "deadlines and latencies share one time base")
        if scraper is not None and scraper.target is not cluster:
            raise ValueError(
                "scraper.target is not this driver's cluster — build "
                "the Scraper over the same ClusterEngine so its samples "
                "describe the fleet this trace actually drives")
        if autoscale and (scraper is None or scraper.autoscale is None):
            raise ValueError(
                "autoscale=True needs a scraper built with an "
                "AutoscalePolicy (Scraper(cluster, autoscale=policy)) — "
                "the recommendation series IS the policy's output")
        self.cluster = cluster
        self.clock = clock
        self.step_time_s = float(step_time_s)
        self.max_steps = max_steps
        self.check_invariants = check_invariants
        self.check_every = max(int(check_every), 1)
        #: telemetry scraper driven at every round boundary; optional
        self.scraper = scraper
        #: when True, the scraper's AutoscalePolicy recommendation is
        #: APPLIED to the live cluster through ``scale_to`` after each
        #: round — autoscaling policies testable as code, chip-free
        self.autoscale = bool(autoscale)
        #: disaggregation's headline liveness gate: every caught-up row
        #: on a full-speed decode replica must gain a token EVERY step,
        #: whatever prompt flood the prefill pool is chewing
        self.check_decode_progress = bool(check_decode_progress)

    def run(self, trace) -> ClusterRunResult:
        cluster = self.cluster
        clock = self.clock
        records = build_trace_records(trace)
        result = ClusterRunResult(
            records=[records[r.request_id] for r in trace],
            step_time_s=self.step_time_s)
        pending = deque(sorted(trace, key=lambda r: (r.arrival_s,
                                                     r.request_id)))
        t_start = clock.now()
        steps = 0
        while pending or cluster.has_unfinished():
            if not cluster.has_unfinished() and pending \
                    and pending[0].arrival_s > clock.now():
                # idle: jump to the next arrival — but never past a
                # scheduled fault, which must fire (and recover) on time
                target = pending[0].arrival_s
                ft = cluster.next_fault_t()
                if ft is not None and clock.now() < ft < target:
                    target = ft
                clock.advance_to(target)
            while pending and pending[0].arrival_s <= clock.now():
                req = pending.popleft()
                rec = records[req.request_id]
                rec.submitted_at = clock.now()
                session = None if req.prefix_cohort < 0 \
                    else f"cohort-{req.prefix_cohort}"
                try:
                    cluster.add_request(
                        list(req.prompt_token_ids),
                        max_new_tokens=req.max_new_tokens,
                        temperature=req.temperature,
                        top_k=getattr(req, "top_k", 0) or None,
                        top_p=getattr(req, "top_p", 1.0),
                        seed=getattr(req, "seed", None),
                        eos_token_id=req.eos_token_id,
                        deadline_s=req.deadline_s,
                        abort_after_s=getattr(req, "abort_after_s", None),
                        request_id=req.request_id, session_id=session,
                        tenant_id=getattr(req, "tenant_id", None))
                    rec.status = "waiting"
                except RequestRejected:
                    self._absorb(rec, cluster.outputs()[req.request_id],
                                 clock.now())
            # the clock advances BEFORE the round (Driver's discipline):
            # fault firings, requeues, sheds, and token commits all land
            # at the round's END time. An idle-but-faulted cluster still
            # rounds through here so its state machine keeps moving.
            clock.advance(self.step_time_s)
            before = None
            if self.check_decode_progress:
                before = self._decode_rows(cluster)
            touched = cluster.step()
            steps += 1
            now = clock.now()
            if before:
                result.decode_progress_checks += \
                    self._assert_decode_progress(cluster, before)
            for out in touched:
                rec = records.get(out.request_id)
                if rec is not None:
                    self._absorb(rec, out, now)
            snap_parked = len(cluster._parked)
            waiting = running = 0
            for rid, pool in cluster.live_pools():
                util = pool.utilization
                prev = result.per_replica_peak_utilization.get(rid, 0.0)
                result.per_replica_peak_utilization[rid] = max(prev, util)
            for rep in cluster.replicas:
                if rep.engine is None:
                    continue
                waiting += rep.engine.scheduler.queue_depth()
                running += len(rep.engine.scheduler.running)
            result.peak_parked = max(result.peak_parked, snap_parked)
            result.peak_queue_depth = max(result.peak_queue_depth,
                                          waiting + snap_parked)
            result.peak_running = max(result.peak_running, running)
            if self.check_invariants and steps % self.check_every == 0:
                for _rid, pool in cluster.live_pools():
                    # a failure raises InvariantViolation out of the run
                    # with the pool snapshot attached — proof-by-survival
                    pool.check_invariants()
                    result.invariant_checks += 1
            if self.scraper is not None:
                scraped = self.scraper.maybe_scrape(now)
                if scraped and self.autoscale:
                    want = self.scraper.last_desired_replicas
                    if want is not None \
                            and want != cluster.provisioned_replicas():
                        result.scale_events += 1
                        # scale_to returns the outputs its requeues
                        # touched (a shrink's budget-exhausted sheds
                        # included) — absorb them at THIS boundary so
                        # their timestamps are honest
                        for out in cluster.scale_to(want):
                            rec = records.get(out.request_id)
                            if rec is not None:
                                self._absorb(rec, out, now)
            if steps >= self.max_steps:
                raise RuntimeError(
                    f"cluster load run did not drain within "
                    f"{self.max_steps} steps ({len(pending)} pending, "
                    f"{sum(1 for o in cluster.outputs().values() if not o.finished)} unfinished)")
        outs = cluster.outputs()
        for rid, rec in records.items():
            out = outs.get(rid)
            if out is not None and out.finished \
                    and rec.finished_at is None:
                self._absorb(rec, out, clock.now())
            if rid in cluster._requests:
                rec.num_retries = cluster.request_retries(rid)
        result.steps = steps
        result.duration_s = clock.now() - t_start
        result.metrics = cluster.metrics_snapshot()
        result.tracer = getattr(cluster, "tracer", None)
        if self.scraper is not None:
            # closing sample at drain (single-engine driver discipline)
            self.scraper.finalize(clock.now())
        result.telemetry = self.scraper
        return result

    # ---- decode-progress gate (disaggregated serving) ----
    @staticmethod
    def _decode_rows(cluster):
        """Caught-up rows on decode replicas that WILL step at full
        speed this round: (replica, seq) -> (generation, tokens). Rows
        on slowed replicas are excluded — a slowdown fault legitimately
        skips engine steps, which is latency, not starvation."""
        from ..serving.cluster import ACTIVE_STATES
        rows = {}
        for rep in cluster.replicas:
            if rep.role != "decode" or rep.engine is None \
                    or rep.state not in ACTIVE_STATES \
                    or rep.slow_multiplier != 1.0 \
                    or (rep.flaky_until is not None):
                continue
            for seq in rep.engine.scheduler.running:
                if seq.uncached_len == 1 and seq.tokens:
                    rows[(rep.rid, seq.seq_id)] = (rep.generation,
                                                   len(seq.tokens))
        return rows

    @staticmethod
    def _assert_decode_progress(cluster, before) -> int:
        """Every snapshot row still RUNNING on the same engine body
        must have gained at least one token. Finished / preempted /
        crashed-away rows are exempt (they left the running set, they
        did not starve on it). Returns the number of assertions that
        ran and passed; a violation raises out of the run."""
        checked = 0
        for (rid, sid), (gen, n) in before.items():
            rep = cluster.replicas[rid]
            if rep.engine is None or rep.generation != gen:
                continue
            seq = rep.engine._seqs.get(sid)
            if seq is None or not any(
                    s is seq for s in rep.engine.scheduler.running):
                continue
            if len(seq.tokens) <= n:
                raise AssertionError(
                    f"decode starvation: request {sid!r} on decode "
                    f"replica {rid} held {n} tokens across a full-speed "
                    f"step — the disaggregation contract (decode rows "
                    f"advance every step) is broken")
            checked += 1
        return checked

    #: record folding is IDENTICAL to the single-engine driver's (a
    #: requeued request's token list resets and regrows, so only
    #: genuinely new positions get fresh timestamps) — share the one
    #: implementation so the two byte-compared artifacts cannot fork
    _absorb = staticmethod(Driver._absorb)


def run_cluster_workload(cluster, clock, spec_or_trace,
                         **driver_kw) -> ClusterRunResult:
    """One-call convenience: compile (if given a spec) and drive."""
    trace = spec_or_trace.compile() if hasattr(spec_or_trace, "compile") \
        else spec_or_trace
    return ClusterDriver(cluster, clock, **driver_kw).run(trace)


__all__ = ["ClusterDriver", "ClusterRunResult", "run_cluster_workload"]
