"""Reduce a load run to a stable JSON SLO/pressure artifact.

``build_report`` folds a :class:`~paddle_tpu.loadgen.driver.RunResult`
(plus, optionally, the spec and trace that produced it) into one plain
dict: latency percentiles (p50/p90/p99 TTFT, e2e, TPOT), goodput
(finished within the e2e SLO), shed/preempt/reject outcome counts,
KV-page/watermark pressure peaks, and prefix-cache effectiveness.
Percentiles here are EXACT (computed over every request record, not the
metrics reservoir) — the in-engine histograms exist so a live server has
percentiles too; the harness has the full population and uses it.

``report_json`` is the artifact writer: floats rounded to a fixed
precision and keys sorted, so the same run serializes to the same bytes
— the determinism gate (tests/test_loadgen.py) compares artifacts, not
hand-picked fields. Everything in the report derives from the virtual
clock and counters; nothing reads wall-clock time.
"""
from __future__ import annotations

import json

from ..serving.metrics import percentile_of
# one rounding discipline shared with the trace export: report bytes and
# trace bytes must never drift apart on float precision
from ..serving.tracing import _round_floats
from .workload import trace_fingerprint

SCHEMA_VERSION = 1


def _dist(values) -> dict:
    """{count, mean, p50, p90, p99, min, max} over a value list (exact;
    Nones when the population is empty)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return {"count": 0, "mean": None, "p50": None, "p90": None,
                "p99": None, "min": None, "max": None}
    return {"count": len(vals), "mean": sum(vals) / len(vals),
            "p50": percentile_of(vals, 50), "p90": percentile_of(vals, 90),
            "p99": percentile_of(vals, 99), "min": min(vals),
            "max": max(vals)}


def _core_sections(result, spec, trace) -> dict:
    """The sections the single-engine and cluster artifacts share —
    schema version, workload identity, request outcomes, EXACT latency
    percentiles, goodput, and base throughput — built once so the two
    builders cannot silently fork (both artifacts are byte-compared by
    the determinism gates)."""
    recs = result.records
    statuses = result.by_status()
    finished = [r for r in recs if r.status == "finished"]
    total = len(recs)
    good = sum(1 for r in recs if r.in_slo)
    tokens = sum(r.num_tokens for r in recs)
    return {
        "schema_version": SCHEMA_VERSION,
        "workload": {
            "spec": spec.describe() if spec is not None else None,
            "trace_fingerprint": trace_fingerprint(trace)
            if trace is not None else None,
            "num_requests": total,
        },
        "requests": {
            "total": total,
            "finished": statuses.get("finished", 0),
            "shed": statuses.get("shed", 0),
            "aborted": statuses.get("aborted", 0),
            "cancelled": statuses.get("cancelled", 0),
            "unresolved": sum(statuses.get(s, 0)
                              for s in ("pending", "waiting", "running",
                                        "preempted")),
            "preempted_requests": sum(1 for r in recs
                                      if r.num_preemptions > 0),
        },
        "latency": {
            "ttft_s": _dist([r.ttft_s for r in finished]),
            "e2e_s": _dist([r.e2e_s for r in finished]),
            "tpot_s": _dist([r.tpot_s for r in finished]),
        },
        "goodput": {
            "completed_in_slo": good,
            "offered": total,
            "goodput_fraction": good / total if total else None,
        },
        "throughput": {
            "tokens_generated": tokens,
            "duration_s": result.duration_s,
            "tokens_per_s": tokens / result.duration_s
            if result.duration_s > 0 else None,
            "steps": result.steps,
            "step_time_s": result.step_time_s,
        },
    }


def _breakdown_section(tracer) -> dict:
    """Span-derived latency attribution (queue vs prefill vs decode vs
    stall; serving/tracing.py) for reports built with ``tracer=`` — the
    section that turns a p99 regression into an attributable component
    instead of one opaque number. Only attached when a tracer is given,
    so pre-tracing artifacts byte-persist."""
    from ..serving.tracing import latency_breakdown
    return latency_breakdown(tracer)


def _telemetry_section(result, telemetry):
    """Scraper summary (series tails, fleet-merged latency, alert
    timeline, autoscale story) for runs driven with ``scraper=`` —
    attached only when one exists, so pre-telemetry artifacts
    byte-persist. The FULL series export stays on the scraper
    (``export_json``); the report carries the decision-grade summary."""
    if telemetry is None:
        return None
    out = telemetry.summary()
    scale_events = getattr(result, "scale_events", 0)
    if scale_events:
        out["scale_events"] = scale_events
    return out


def build_report(result, *, spec=None, trace=None, tracer=None,
                 telemetry=None) -> dict:
    """RunResult (+ spec/trace context) -> the artifact dict.

    ``tracer`` (the engine's :class:`~paddle_tpu.serving.tracing.
    RequestTracer`, when one was attached) adds the span-derived
    ``latency_breakdown`` section; it defaults to the tracer the driver
    recorded on the result, so a traced run's report carries the
    breakdown without extra plumbing. ``telemetry`` (the run's
    :class:`~paddle_tpu.telemetry.Scraper`) likewise defaults to the
    one the driver recorded and adds the ``telemetry`` section (fleet
    series tails, merged latency, alert timeline). Reports without
    either are unchanged."""
    if tracer is None:
        tracer = getattr(result, "tracer", None)
    if telemetry is None:
        telemetry = getattr(result, "telemetry", None)
    m = result.metrics or {}
    tokens = sum(r.num_tokens for r in result.records)
    hits = m.get("prefix_cache_hits", 0)
    misses = m.get("prefix_cache_misses", 0)
    report = _core_sections(result, spec, trace)
    report["requests"]["preemptions"] = m.get("preemptions", 0)
    report["throughput"].update({
        "host_dispatches": m.get("host_dispatches", 0),
        "host_dispatches_per_token": m.get("host_dispatches", 0)
        / tokens if tokens else None,
        "burst_tokens": m.get("burst_tokens"),
    })
    report.update({
        "kv_pressure": {
            "peak_page_utilization": result.peak_page_utilization,
            "peak_used_pages": result.peak_used_pages,
            "page_capacity": result.page_capacity,
            # False = the in-run audits RAN (invariant_checks of them)
            # and all passed; None = auditing was disabled, nothing
            # proven. True is unreachable: a failing audit raises out
            # of the run instead of producing a report.
            "over_allocated": False if result.invariant_checks > 0
            else None,
            "invariant_checks": result.invariant_checks,
            "preemptions": m.get("preemptions", 0),
            "decode_compiles": m.get("decode_compiles", 0),
        },
        "queue": {
            "peak_queue_depth": result.peak_queue_depth,
            "peak_running": result.peak_running,
            "queue_age_p99_s": m.get("queue_age_p99_s"),
            "max_queue_wait_s": m.get("max_queue_wait_s"),
        },
        "prefix_cache": {
            "hits": hits,
            "misses": misses,
            "hit_rate": hits / (hits + misses) if hits + misses else None,
            "shared_page_fraction": m.get("shared_page_fraction"),
            "cow_copies": m.get("cow_copies", 0),
            "pinned_prefix_hits": m.get("pinned_prefix_hits", 0),
        },
    })
    if m.get("kv_host_pages") is not None:
        # two-tier engines only (serving/kv_tier.py) — single-tier
        # artifacts byte-persist without the section
        report["kv_tiering"] = {
            "hbm_pages": m.get("kv_hbm_pages"),
            "host_pages": m.get("kv_host_pages"),
            "spills": m.get("kv_spills", 0),
            "prefetch_hits": m.get("kv_prefetch_hits", 0),
            "prefetch_stalls": m.get("kv_prefetch_stalls", 0),
            "resident_fraction": m.get("kv_resident_fraction"),
            "host_chain_promotions": m.get("kv_host_chain_promotions"),
        }
    if m.get("tenants") is not None:
        # multi-tenant engines only (paddle_tpu.tenancy) — classic
        # artifacts byte-persist without the section. The engine-side
        # ledgers carry cost attribution (tokens, KV byte-seconds,
        # adapter-slot seconds); the record-derived block carries the
        # EXACT per-tenant latency split the isolation gate scores.
        by_tenant: dict = {}
        for r in result.records:
            tid = getattr(r, "tenant_id", None) or "_default"
            by_tenant.setdefault(tid, []).append(r)
        report["tenants"] = {
            "ledgers": m["tenants"],
            "quota_shed_requests": m.get("quota_shed_requests", 0),
            "adapter_slots": m.get("adapter_slots"),
            "per_tenant": {
                tid: {
                    "requests": len(rs),
                    "finished": sum(1 for x in rs
                                    if x.status == "finished"),
                    "shed": sum(1 for x in rs if x.status == "shed"),
                    "ttft_s": _dist([x.ttft_s for x in rs
                                     if x.status == "finished"]),
                } for tid, rs in sorted(by_tenant.items())},
        }
    if spec is not None and \
            getattr(spec, "lane", "interactive") == "offline_batch":
        # throughput-not-latency lane (ROADMAP 5d): batch tokens/s is
        # the headline; total-token rate credits the prefill work a
        # generated-only rate hides on long-document batches
        prompt_toks = sum(r.prompt_len for r in result.records)
        dur = result.duration_s
        report["offline_batch"] = {
            "batch_tokens_per_s": tokens / dur if dur > 0 else None,
            "batch_total_tokens_per_s":
                (prompt_toks + tokens) / dur if dur > 0 else None,
            "prompt_tokens": prompt_toks,
        }
    if tracer is not None:
        report["latency_breakdown"] = _breakdown_section(tracer)
    tel = _telemetry_section(result, telemetry)
    if tel is not None:
        report["telemetry"] = tel
    return report


def build_cluster_report(result, *, spec=None, trace=None,
                         faults=None, tracer=None,
                         telemetry=None) -> dict:
    """ClusterRunResult (+ spec/trace/fault-script context) -> the
    fleet artifact dict: everything the single-engine report has at
    fleet scope (exact percentiles over every request record, goodput,
    outcome counts) PLUS the robustness story — retries and
    budget-sheds, crash/drain/flaky/recovery counts, per-replica
    state-machine time (time-in-degraded-state included), degradation
    ladder transitions, and the fault script that caused it all.
    Serialize with :func:`report_json` for the byte-identity gate.
    ``tracer`` and ``telemetry`` behave exactly like
    :func:`build_report`'s; the telemetry section additionally carries
    the autoscale story (``scale_events``, the cluster's scale_up/
    scale_down counters ride ``cluster`` below)."""
    if tracer is None:
        tracer = getattr(result, "tracer", None)
    if telemetry is None:
        telemetry = getattr(result, "telemetry", None)
    recs = result.records
    m = result.metrics or {}
    reps = m.get("replicas", [])
    tis = m.get("time_in_state_s", {})

    def _csum(key):
        return sum(r["counters"].get(key, 0) for r in reps)

    report = _core_sections(result, spec, trace)
    report["requests"].update({
        "preemptions": _csum("preemptions"),
        "deadline_aborts": _csum("deadline_aborts"),
        "nonfinite_rows": _csum("nonfinite_rows"),
        "retried_requests": sum(1 for r in recs
                                if r.num_retries > 0),
    })
    report.update({
        "queue": {
            "peak_queue_depth": result.peak_queue_depth,
            "peak_running": result.peak_running,
            "peak_parked": result.peak_parked,
        },
        "kv_pressure": {
            "peak_page_utilization": max(
                result.per_replica_peak_utilization.values(), default=0.0),
            "per_replica_peak_utilization": {
                str(k): v for k, v
                in sorted(result.per_replica_peak_utilization.items())},
            "over_allocated": False if result.invariant_checks > 0
            else None,
            "invariant_checks": result.invariant_checks,
        },
        "cluster": {
            "num_replicas": m.get("num_replicas"),
            "retry_budget": m.get("retry_budget"),
            "retries": m.get("retries", 0),
            "retry_budget_sheds": m.get("retry_budget_sheds", 0),
            "fleet_unavailable_sheds": m.get("fleet_unavailable_sheds", 0),
            "crashes": m.get("crashes", 0),
            "recoveries": m.get("recoveries", 0),
            "drains": m.get("drains", 0),
            "flaky_steps": m.get("flaky_steps", 0),
            "engine_errors": m.get("engine_errors", 0),
            "kv_pressure_faults": m.get("kv_pressure_faults", 0),
            "slowdown_faults": m.get("slowdown_faults", 0),
            "router_decisions": m.get("router_decisions", 0),
            "affinity_hits": m.get("affinity_hits", 0),
            "state_transitions": m.get("state_transitions", 0),
            "scale_ups": m.get("scale_ups", 0),
            "scale_downs": m.get("scale_downs", 0),
            "provisioned_replicas": m.get("provisioned_replicas"),
            "time_in_state_s": tis,
            "time_degraded_s": tis.get("degraded", 0.0),
            "degradation": {
                "escalations": _csum("degradation_escalations"),
                "restorations": _csum("degradation_restorations"),
                "final_levels": [r.get("degradation_level", 0)
                                 for r in reps],
            },
            # fleet-level crash dumps + every replica's own dumps
            # (nonfinite aborts, invariant violations) — carried across
            # replica deaths like the other per-replica counters
            "flight_dumps": m.get("flight_dumps", 0)
            + _csum("flight_dumps"),
            "faults": faults.describe() if faults is not None else None,
            "per_replica": reps,
        },
    })
    dis = m.get("disagg")
    if dis is not None:
        # disaggregated (roles=) runs only — colocated artifacts
        # byte-persist without the section. Page transfers, stalls, and
        # fleet-prefix hits are per-replica carried counters summed
        # fleet-wide here; the fabric/fleet-prefix dicts come from the
        # cluster snapshot verbatim.
        report["disagg"] = {
            "collapsed": dis.get("collapsed"),
            "collapses": dis.get("counters", {}).get("collapses", 0),
            "collapse_restores":
                dis.get("counters", {}).get("collapse_restores", 0),
            "handoffs": dis.get("counters", {}).get("handoffs", 0),
            "transfer_drops":
                dis.get("counters", {}).get("transfer_drops", 0),
            "transfer_requeues":
                dis.get("counters", {}).get("transfer_requeues", 0),
            "transfer_slow_faults":
                dis.get("counters", {}).get("transfer_slow_faults", 0),
            "transfer_drop_faults":
                dis.get("counters", {}).get("transfer_drop_faults", 0),
            "fabric": dis.get("fabric"),
            "fleet_prefix": dis.get("fleet_prefix"),
            "kv_pages_transferred": _csum("kv_pages_transferred"),
            "transfer_stalls": _csum("transfer_stalls"),
            "fleet_prefix_hits": _csum("fleet_prefix_hits"),
            "prefill_queue_depth": dis.get("prefill_queue_depth"),
            "decode_queue_depth": dis.get("decode_queue_depth"),
            "decode_progress_checks":
                getattr(result, "decode_progress_checks", 0),
            "roles": [r.get("role") for r in reps],
        }
    if tracer is not None:
        report["latency_breakdown"] = _breakdown_section(tracer)
    tel = _telemetry_section(result, telemetry)
    if tel is not None:
        report["telemetry"] = tel
    return report


def report_json(report) -> str:
    """Stable serialization: sorted keys, fixed float precision — the
    byte-identity the determinism gate compares."""
    return json.dumps(_round_floats(report), sort_keys=True, indent=1)


__all__ = ["SCHEMA_VERSION", "build_cluster_report", "build_report",
           "report_json"]
