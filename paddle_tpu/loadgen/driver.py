"""Virtual-clock load driver over the serving engine.

The serving stack takes ``now_fn`` everywhere (serving/scheduler.py,
serving/engine.py, serving/metrics.py), so "time" during a load run is a
:class:`VirtualClock` the driver alone advances: arrivals, deadline
shedding, preemption, queue-age gauges and every recorded latency are
deterministic functions of the trace and the engine's seed — the same
run reproduces bit for bit, with no wall-clock noise and no sleeping.

Time model: one engine ``step()`` costs ``step_time_s`` virtual seconds
(a fixed service-time abstraction — the CPU tier measures scheduling
behavior and dispatch counts, not kernel wall-clock; docs/BENCH.md).
Requests are injected when the clock reaches their trace arrival time; a
request arriving mid-step waits for the step boundary, exactly like a
real serving loop polling its intake queue once per iteration. Tokens
committed by a step are stamped at the step's END. Under burst mode
(``burst_tokens > 1``) a whole burst lands at one boundary and its
tokens share a timestamp — admission/shed latency quantizes to burst
length by design, and the determinism gate covers that regime too.

The driver is also the watermark auditor: with ``check_invariants`` on
(the default) it runs ``pool.check_invariants()`` every
``check_every`` steps and asserts the pool never over-allocates —
the overload scenario's "watermark gates holding" criterion is checked
during the run, not inferred afterwards.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from ..serving.engine import RequestRejected


class VirtualClock:
    """Monotonic virtual time; pass ``clock.now`` as the engine's
    ``now_fn``. Only the driver advances it."""

    def __init__(self, t0=0.0):
        self._t = float(t0)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float):
        if dt < 0:
            raise ValueError(f"clock cannot go backwards (dt={dt})")
        self._t += dt

    def advance_to(self, t: float):
        if t > self._t:
            self._t = t


@dataclass
class RequestRecord:
    """Per-request observed outcome of one load run."""
    request_id: str
    arrival_s: float
    prompt_len: int
    max_new_tokens: int
    deadline_s: float | None
    slo_e2e_s: float | None
    prefix_cohort: int = -1
    #: owning tenant when the trace declares a tenant mix, else None
    tenant_id: str | None = None
    #: when the driver actually handed the request to the engine (the
    #: step boundary at/after arrival_s — a real intake queue's poll)
    submitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    #: virtual timestamp of every streamed token, in commit order
    token_times: list = field(default_factory=list)
    num_tokens: int = 0
    status: str = "pending"
    finish_reason: str | None = None
    num_preemptions: int = 0
    #: cluster runs only: times the request was requeued to another
    #: replica after a crash/drain (0 under the single-engine driver)
    num_retries: int = 0

    # latencies anchor on the TRACE arrival time, not submitted_at: the
    # client started waiting when the request arrived, and the
    # sub-step-boundary injection delay is part of what it perceived
    @property
    def ttft_s(self) -> float | None:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.arrival_s

    @property
    def e2e_s(self) -> float | None:
        if self.finished_at is None:
            return None
        return self.finished_at - self.arrival_s

    @property
    def tpot_s(self) -> float | None:
        """Mean inter-token time after the first token."""
        if self.num_tokens < 2 or self.first_token_at is None \
                or self.finished_at is None:
            return None
        return (self.finished_at - self.first_token_at) \
            / (self.num_tokens - 1)

    @property
    def in_slo(self) -> bool:
        """Goodput test: finished AND (no e2e SLO or beat it)."""
        if self.status != "finished":
            return False
        return self.slo_e2e_s is None or \
            (self.e2e_s is not None and self.e2e_s <= self.slo_e2e_s)


@dataclass
class RunResult:
    """Everything one load run observed, ready for loadgen/report.py."""
    records: list                      # [RequestRecord] in trace order
    duration_s: float = 0.0
    steps: int = 0
    step_time_s: float = 0.0
    peak_page_utilization: float = 0.0
    peak_used_pages: int = 0
    page_capacity: int = 0
    peak_queue_depth: int = 0
    peak_running: int = 0
    metrics: dict = field(default_factory=dict)   # engine snapshot at end
    #: pool audits that RAN and passed during the run (a failing audit
    #: raises out of run() — a RunResult you hold passed every one; 0
    #: means auditing was disabled, i.e. nothing was proven)
    invariant_checks: int = 0
    #: the engine's RequestTracer when one was attached (serving/
    #: tracing.py) — build_report picks it up for the span-derived
    #: latency-breakdown section; None otherwise
    tracer: object = None
    #: the telemetry Scraper when one drove the run (paddle_tpu.
    #: telemetry) — build_report attaches its summary (series tails,
    #: fleet latency, alert timeline); None otherwise
    telemetry: object = None

    def by_status(self) -> dict:
        out: dict[str, int] = {}
        for r in self.records:
            out[r.status] = out.get(r.status, 0) + 1
        return out


class Driver:
    """Replays a compiled trace (loadgen/workload.py) against an engine
    whose ``now_fn`` is this driver's clock.

    ``engine`` must have been constructed with ``now_fn=clock.now`` —
    the driver refuses mismatched clocks it can detect (an engine on
    wall-clock time would shed against a clock the driver never
    advances, silently voiding every deadline in the trace).
    """

    def __init__(self, engine, clock: VirtualClock, *, step_time_s=0.01,
                 max_steps=200_000, check_invariants=True, check_every=1,
                 scraper=None):
        if step_time_s <= 0:
            raise ValueError("step_time_s must be > 0")
        if scraper is not None and scraper.target is not engine:
            raise ValueError(
                "scraper.target is not this driver's engine — build the "
                "Scraper over the same engine so its samples describe "
                "the fleet this trace actually drives")
        # bound-method equality (== not `is`: attribute access creates a
        # fresh method object every time)
        if engine._now != clock.now:
            raise ValueError(
                "engine.now_fn is not this driver's clock — construct the "
                "engine with now_fn=clock.now so deadlines and latencies "
                "share one time base")
        self.engine = engine
        self.clock = clock
        self.step_time_s = float(step_time_s)
        self.max_steps = max_steps
        self.check_invariants = check_invariants
        self.check_every = max(int(check_every), 1)
        #: telemetry scraper (paddle_tpu.telemetry.Scraper) driven at
        #: every step boundary on this driver's clock; optional
        self.scraper = scraper

    def run(self, trace) -> RunResult:
        eng = self.engine
        clock = self.clock
        records = build_trace_records(trace)
        result = RunResult(records=[records[r.request_id] for r in trace],
                           step_time_s=self.step_time_s,
                           page_capacity=eng.pool.capacity)
        pending = deque(sorted(trace, key=lambda r: (r.arrival_s,
                                                     r.request_id)))
        t_start = clock.now()
        steps = 0
        while pending or eng.has_unfinished():
            if not eng.has_unfinished() and pending \
                    and pending[0].arrival_s > clock.now():
                # idle engine: jump straight to the next arrival
                clock.advance_to(pending[0].arrival_s)
            while pending and pending[0].arrival_s <= clock.now():
                req = pending.popleft()
                rec = records[req.request_id]
                rec.submitted_at = clock.now()
                try:
                    eng.add_request(
                        list(req.prompt_token_ids),
                        max_new_tokens=req.max_new_tokens,
                        temperature=req.temperature,
                        top_k=getattr(req, "top_k", 0) or None,
                        top_p=getattr(req, "top_p", 1.0),
                        seed=getattr(req, "seed", None),
                        eos_token_id=req.eos_token_id,
                        deadline_s=req.deadline_s,
                        abort_after_s=getattr(req, "abort_after_s", None),
                        request_id=req.request_id,
                        tenant_id=getattr(req, "tenant_id", None))
                    rec.status = "waiting"
                except RequestRejected:
                    # the engine recorded a finalized aborted output;
                    # sweep it into the record like any other terminal
                    self._absorb(rec, eng.outputs()[req.request_id],
                                 clock.now())
            if not eng.has_unfinished():
                continue
            # the clock advances BEFORE the launch: the step's work (and
            # its shed decisions, token commits, and the engine's own
            # TTFT/TPOT histograms) all land at the step's END time —
            # one time base shared by driver records and engine metrics
            clock.advance(self.step_time_s)
            touched = eng.step()
            steps += 1
            now = clock.now()
            for out in touched:
                rec = records.get(out.request_id)
                if rec is not None:
                    self._absorb(rec, out, now)
            pool = eng.pool
            result.peak_page_utilization = max(
                result.peak_page_utilization, pool.utilization)
            result.peak_used_pages = max(result.peak_used_pages,
                                         pool.used_pages)
            result.peak_queue_depth = max(
                result.peak_queue_depth, eng.scheduler.queue_depth())
            result.peak_running = max(result.peak_running,
                                      len(eng.scheduler.running))
            if self.check_invariants and steps % self.check_every == 0:
                # a failure RAISES — there is no "run completed but the
                # pool over-allocated" outcome, only proof-by-survival,
                # which is why the report keys off the audit COUNT
                pool.check_invariants()
                assert pool.used_pages <= pool.capacity
                assert pool.used_pages + pool.free_pages == pool.capacity
                result.invariant_checks += 1
            if self.scraper is not None:
                # telemetry samples land at the step's END time — the
                # same boundary token commits and metrics share
                self.scraper.maybe_scrape(now)
            if steps >= self.max_steps:
                raise RuntimeError(
                    f"load run did not drain within {self.max_steps} "
                    f"steps ({len(pending)} pending, "
                    f"{len(eng.scheduler.running)} running, "
                    f"{eng.scheduler.queue_depth()} waiting)")
        # final sweep: terminal statuses the last step may not have
        # surfaced through its touched set (e.g. shed before any step)
        outs = eng.outputs()
        for rid, rec in records.items():
            out = outs.get(rid)
            if out is not None and out.finished \
                    and rec.finished_at is None:
                self._absorb(rec, out, clock.now())
        result.steps = steps
        result.duration_s = clock.now() - t_start
        result.metrics = eng.metrics_snapshot()
        result.tracer = getattr(eng, "tracer", None)
        if self.scraper is not None:
            # closing sample at drain: the exported series cover the
            # run's true end, not just the last scheduled interval
            self.scraper.finalize(clock.now())
        result.telemetry = self.scraper
        return result

    @staticmethod
    def _absorb(rec: RequestRecord, out, now: float):
        """Fold one touched RequestOutput into the record at time now.

        Shared verbatim by the cluster driver (loadgen/cluster.py): a
        requeued request's token list resets and regrows, so ``new`` is
        non-positive until genuinely new positions appear — only those
        get fresh timestamps, which is exactly this logic."""
        new = len(out.token_ids) - rec.num_tokens
        if new > 0:
            if rec.first_token_at is None:
                rec.first_token_at = now
            rec.token_times.extend([now] * new)
            rec.num_tokens = len(out.token_ids)
        rec.status = out.status
        rec.num_preemptions = out.num_preemptions
        if out.finished and rec.finished_at is None:
            rec.finished_at = now
            rec.finish_reason = out.finish_reason


def build_trace_records(trace) -> dict:
    """Validate trace ids and build the per-request record map — shared
    by the single-engine and cluster drivers so the two byte-compared
    artifacts can never fork on record construction."""
    ids = [r.request_id for r in trace]
    if len(set(ids)) != len(ids):
        dups = sorted({i for i in ids if ids.count(i) > 1})
        raise ValueError(
            f"trace has duplicate request_ids {dups[:5]} — "
            f"concatenated specs must use distinct seeds (ids embed "
            f"the seed) or distinct explicit ids")
    return {r.request_id: RequestRecord(
        request_id=r.request_id, arrival_s=r.arrival_s,
        prompt_len=len(r.prompt_token_ids),
        max_new_tokens=r.max_new_tokens, deadline_s=r.deadline_s,
        slo_e2e_s=r.slo_e2e_s, prefix_cohort=r.prefix_cohort,
        tenant_id=getattr(r, "tenant_id", None))
        for r in trace}


def run_workload(engine, clock, spec_or_trace, **driver_kw) -> RunResult:
    """One-call convenience: compile (if given a spec) and drive."""
    trace = spec_or_trace.compile() if hasattr(spec_or_trace, "compile") \
        else spec_or_trace
    return Driver(engine, clock, **driver_kw).run(trace)


__all__ = ["Driver", "RequestRecord", "RunResult", "VirtualClock",
           "run_workload"]
