"""paddle_tpu.loadgen — serving load harness on a virtual clock.

The measurement substrate for the serving stack (ROADMAP item 5): seeded
workload specs compile to timed request traces, a driver replays them
against :class:`~paddle_tpu.serving.LLMEngine` on a virtual clock, and a
reducer turns the outcomes into a stable JSON SLO artifact. Everything
is deterministic — same spec seed, same engine seed, same report bytes —
so latency/goodput behavior is regression-testable on the CPU tier
(docs/BENCH.md has the schema and how to read the numbers).

- :mod:`workload` — ``WorkloadSpec`` (Poisson/deterministic arrivals,
  prompt/output length mixes, shared-prefix cohorts, per-request SLOs)
  -> ``compile()`` -> ``[TraceRequest]`` + ``trace_fingerprint``.
- :mod:`driver` — ``VirtualClock`` + ``Driver``: injects arrivals,
  steps the engine, stamps per-token virtual timestamps, audits pool
  invariants, returns a ``RunResult`` of ``RequestRecord``\\ s.
- :mod:`report` — ``build_report``/``report_json``: p50/p90/p99 TTFT,
  e2e, TPOT; goodput; shed/preempt/reject counts; KV watermark
  pressure; prefix-cache effectiveness.

Typical use::

    from paddle_tpu.loadgen import (WorkloadSpec, VirtualClock, Driver,
                                    build_report, report_json)
    spec = WorkloadSpec(num_requests=200, arrival="poisson",
                        arrival_rate=40.0, shared_prefix_fraction=0.5,
                        shared_prefix_len=16, deadline_s=0.5,
                        slo_e2e_s=2.0, seed=7)
    clock = VirtualClock()
    engine = LLMEngine(model, now_fn=clock.now, ...)
    result = Driver(engine, clock, step_time_s=0.01).run(spec.compile())
    print(report_json(build_report(result, spec=spec,
                                   trace=spec.compile())))
"""
from .workload import (ARRIVALS, LANES,  # noqa: F401
                       LONG_CONTEXT_CEILING, TraceRequest, WorkloadSpec,
                       trace_fingerprint)
from .driver import (Driver, RequestRecord, RunResult,  # noqa: F401
                     VirtualClock, run_workload)
from .cluster import (ClusterDriver, ClusterRunResult,  # noqa: F401
                      run_cluster_workload)
from .report import (SCHEMA_VERSION, build_cluster_report,  # noqa: F401
                     build_report, report_json)

__all__ = ["ARRIVALS", "LANES", "LONG_CONTEXT_CEILING",
           "ClusterDriver", "ClusterRunResult", "Driver",
           "RequestRecord", "RunResult", "SCHEMA_VERSION", "TraceRequest",
           "VirtualClock", "WorkloadSpec", "build_cluster_report",
           "build_report", "report_json", "run_cluster_workload",
           "run_workload", "trace_fingerprint"]
