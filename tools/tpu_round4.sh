#!/usr/bin/env bash
# Round-4 TPU evidence runbook — run when the pool chip is reachable.
# ONE TPU process at a time (axon claim discipline, .claude/skills/verify);
# each step exits cleanly before the next starts.
#
#   bash tools/tpu_round4.sh [audit|bench|opbench|all]
#
# Produces:
#   docs/PERF_AUDIT.json   — regenerated matmul/attention/step sections
#   bench JSON on stdout   — llama_125m + llama_1b (the driver's format)
#   tools/op_bench_baseline.json — TPU per-op baseline for the gate
set -euo pipefail
cd "$(dirname "$0")/.."

what="${1:-all}"

probe() {
  echo "== probing the chip (120s) =="
  timeout 120 python -c "import jax; print(jax.devices())" || {
    echo "chip unreachable; aborting" >&2
    exit 2
  }
}

case "$what" in
  audit|all)
    probe
    echo "== perf audit: matmul (corrected marginal method) =="
    timeout 900 python tools/perf_audit.py matmul
    echo "== perf audit: attention =="
    timeout 900 python tools/perf_audit.py attention
    echo "== perf audit: step breakdown =="
    timeout 1200 python tools/perf_audit.py step
    ;;&
  bench|all)
    probe
    echo "== bench: llama_125m + llama_1b =="
    timeout 2400 python bench.py
    ;;&
  opbench|all)
    probe
    echo "== op bench: record the TPU baseline =="
    timeout 900 python tools/op_bench.py --record --no-collective
    ;;&
  audit|bench|opbench|all)
    : ;;  # recognized
  *)
    echo "usage: $0 [audit|bench|opbench|all]" >&2
    exit 1
    ;;
esac
echo "done: update docs/PERF.md tables from docs/PERF_AUDIT.json and drop"
echo "the pending-regeneration banners for sections now backed by raw data."
