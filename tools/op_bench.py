"""Per-op perf regression gate (round-3 verdict item 4).

The reference CI diffs op benchmarks against a recorded baseline
(/root/reference/tools/ci_op_benchmark.sh + check_op_benchmark_result.py);
this is the TPU-native equivalent: time the registry's hot set on the
current backend, diff against a checked-in baseline JSON, fail on
regressions beyond tolerance.

Usage:
  python tools/op_bench.py                 # run + gate vs baseline
  python tools/op_bench.py --record        # re-record the baseline
  python tools/op_bench.py --json          # print results, no gate

Baselines are per-backend (cpu / tpu-<kind>): timings are only comparable
on the same part. CI runs the cpu gate; record a tpu baseline when the
chip profile changes. Gate logic mirrors check_op_benchmark_result.py:
relative slowdown beyond --tolerance (default 2.0x) on any op fails with
rc 1. The generous default absorbs CI machine noise while still catching
the "round N+1 made rms_norm 3x slower" class; tighten per deployment.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(REPO, "tools", "op_bench_baseline.json")


def _timed_chain(fn, x, iters, warmup=3):
    """Chained same-shape timing: each call consumes the previous output so
    async dispatch cannot overlap the measured work (tools/perf_audit.py's
    method)."""
    import jax
    y = x
    for _ in range(warmup):
        y = fn(y)
    jax.block_until_ready(y)
    reps = []
    for _ in range(3):
        y = x
        t0 = time.perf_counter()
        for _ in range(iters):
            y = fn(y)
        jax.block_until_ready(y)
        reps.append((time.perf_counter() - t0) / iters)
    return min(reps)


def _cases():
    """The hot set: one representative shape per op family. Each case
    returns (name, fn: array -> same-shape array, x0, iters)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.dispatch import OPS

    rng = np.random.default_rng(0)
    cases = []

    # matmul 512^2 (MXU path)
    w = jnp.asarray(rng.standard_normal((512, 512)).astype(np.float32))
    m0 = jnp.asarray(rng.standard_normal((512, 512)).astype(np.float32))
    matmul = jax.jit(lambda a: OPS["matmul"](a, w) * 1e-3)
    cases.append(("matmul_512", matmul, m0, 50))

    # attention (composed SDPA) b=2 s=128 h=4 d=64
    q0 = jnp.asarray(rng.standard_normal((2, 128, 4, 64)).astype(np.float32))
    sdpa = jax.jit(lambda q: OPS["scaled_dot_product_attention"](
        q, q, q, causal=True) * 0.5 + q * 0.5)
    cases.append(("sdpa_128", sdpa, q0, 20))

    # GQA attention (native grouped k/v path, the llama regime): q 8 heads,
    # k/v 2 heads — regressions in the grouped einsum show up here
    kv0 = jnp.asarray(rng.standard_normal((2, 128, 2, 64)).astype(np.float32))
    sdpa_gqa = jax.jit(lambda q: OPS["scaled_dot_product_attention"](
        q, kv0, kv0, causal=True) * 0.5 + q * 0.5)
    qg = jnp.asarray(rng.standard_normal((2, 128, 8, 64)).astype(np.float32))
    cases.append(("sdpa_gqa_128", sdpa_gqa, qg, 20))

    # norm family: rms_norm + layer_norm [1024, 1024]
    h0 = jnp.asarray(rng.standard_normal((1024, 1024)).astype(np.float32))
    gamma = jnp.ones((1024,), jnp.float32)
    rms = jax.jit(lambda a: OPS["rms_norm"](a, gamma) + a * 1e-6)
    cases.append(("rms_norm_1k", rms, h0, 50))
    ln = jax.jit(lambda a: OPS["layer_norm"](
        a, gamma, nd=1, epsilon=1e-5, has_weight=True, has_bias=False)
        + a * 1e-6)
    cases.append(("layer_norm_1k", ln, h0, 50))

    # softmax + elementwise chain
    sm = jax.jit(lambda a: OPS["softmax"](a, axis=-1) + a * 1e-6)
    cases.append(("softmax_1k", sm, h0, 50))

    # embedding gather [8k vocab, 256] x 4096 ids
    table = jnp.asarray(rng.standard_normal((8192, 256)).astype(np.float32))
    ids0 = jnp.asarray(rng.integers(0, 8192, (4096,)).astype(np.int32))
    emb = jax.jit(lambda i: (OPS["embedding"](
        i, table, padding_idx=None).sum(-1) * 0).astype(jnp.int32) + i)
    cases.append(("embedding_4k", emb, ids0, 50))

    # optimizer update: AdamW-style fused update on a 1M-param vector
    p0 = jnp.asarray(rng.standard_normal((1 << 20,)).astype(np.float32))

    def adamw_like(p):
        g = p * 1e-4
        m = 0.9 * p + 0.1 * g
        v = 0.999 * p * p + 0.001 * g * g
        return p - 1e-3 * (m / (jnp.sqrt(v) + 1e-8) + 0.01 * p)

    cases.append(("adamw_update_1m", jax.jit(adamw_like), p0, 50))

    # conv2d 64ch 56x56 3x3
    img0 = jnp.asarray(rng.standard_normal((2, 64, 56, 56)).astype(np.float32))
    kw = jnp.asarray(rng.standard_normal((64, 64, 3, 3)).astype(np.float32) * 0.01)
    conv = jax.jit(lambda a: OPS["conv2d"](
        a, kw, stride=(1, 1), pad=[(1, 1), (1, 1)], dilation=(1, 1),
        groups=1, channel_last=False, nd=2) * 0.5 + a * 0.5)
    cases.append(("conv2d_56", conv, img0, 20))

    # reduction
    red = jax.jit(lambda a: a - a.mean(axis=-1, keepdims=True))
    cases.append(("mean_center_1k", red, h0, 50))

    return cases


def _collective_case():
    """all_reduce over the virtual CPU mesh (only when >1 device)."""
    import jax
    import jax.numpy as jnp
    if jax.device_count() < 2:
        return None
    from jax.sharding import Mesh, PartitionSpec, NamedSharding
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("x",))
    x0 = jnp.ones((len(devs), 1024, 64), jnp.float32)
    x0 = jax.device_put(x0, NamedSharding(mesh, PartitionSpec("x")))

    @jax.jit
    def allreduce_like(a):
        s = a.sum(axis=0, keepdims=True)  # cross-device reduce under GSPMD
        return a * 0.999 + s * 1e-6

    return ("allreduce_mesh", allreduce_like, x0, 20)


def run(include_collective=True):
    import jax
    dev = jax.devices()[0]
    backend = dev.platform if dev.platform == "cpu" else \
        getattr(dev, "device_kind", "tpu").replace(" ", "-").lower()
    results = {}
    cases = _cases()
    coll = _collective_case() if include_collective else None
    if coll is not None:
        cases.append(coll)
    for name, fn, x0, iters in cases:
        results[name] = round(_timed_chain(fn, x0, iters) * 1e6, 2)  # us
    return {"backend": backend, "unit": "us/op", "ops": results}


def gate(current, baseline, tolerance):
    """Mirror of the reference's check_op_benchmark_result.py comparison:
    report per-op speedup/slowdown; fail when any op exceeds tolerance."""
    failures, report = [], []
    base_ops = baseline.get("ops", {})
    for name, cur_us in sorted(current["ops"].items()):
        base_us = base_ops.get(name)
        if base_us is None:
            report.append(f"  {name:<20} {cur_us:>10.1f} us   (new, no baseline)")
            continue
        ratio = cur_us / base_us if base_us else float("inf")
        flag = "" if ratio <= tolerance else "  << REGRESSION"
        report.append(
            f"  {name:<20} {cur_us:>10.1f} us   baseline {base_us:>10.1f}"
            f"   x{ratio:.2f}{flag}")
        if ratio > tolerance:
            failures.append((name, ratio))
    for name in sorted(set(base_ops) - set(current["ops"])):
        report.append(f"  {name:<20} MISSING from current run")
        failures.append((name, float("nan")))
    return failures, "\n".join(report)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--record", action="store_true",
                    help="write the baseline for this backend")
    ap.add_argument("--json", action="store_true", help="print JSON only")
    ap.add_argument("--tolerance", type=float,
                    default=float(os.environ.get(
                        "PADDLE_TPU_OP_BENCH_TOLERANCE", "2.0")),
                    help="max allowed slowdown ratio vs baseline")
    ap.add_argument("--no-collective", action="store_true")
    args = ap.parse_args()

    current = run(include_collective=not args.no_collective)
    if args.json:
        print(json.dumps(current))
        return 0

    baselines = {}
    if os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            baselines = json.load(f)

    if args.record:
        baselines[current["backend"]] = current
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
        print(f"recorded baseline for backend={current['backend']} "
              f"({len(current['ops'])} ops) -> {BASELINE_PATH}")
        return 0

    baseline = baselines.get(current["backend"])
    if baseline is None:
        print(f"no baseline for backend={current['backend']}; run "
              f"`python tools/op_bench.py --record` first", file=sys.stderr)
        return 2

    failures, report = gate(current, baseline, args.tolerance)
    print(f"op bench gate  backend={current['backend']} "
          f"tolerance={args.tolerance}x")
    print(report)
    if failures:
        print(f"FAIL: {len(failures)} op(s) regressed beyond "
              f"{args.tolerance}x: "
              + ", ".join(f"{n} (x{r:.2f})" for n, r in failures),
              file=sys.stderr)
        return 1
    print("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
