"""MFU audit on the real chip (round-2 verdict 'weak #1').

Measures, and writes to docs/PERF_AUDIT.json for PERF.md:
  1. pure-matmul roofline: best sustained bf16 TF/s over square matmuls —
     the practical ceiling the MFU denominator should be read against;
  2. attention path comparison: XLA composed SDPA vs the Pallas flash
     kernel across sequence lengths (the autotune threshold's evidence);
  3. train-step decomposition on the bench config: forward, forward+
     backward, full fused step (fwd+bwd+AdamW), with achieved model TF/s.

Run: python tools/perf_audit.py  (claims the TPU; run nothing else.)
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def timed(fn, *args, iters=10, warmup=2):
    """Per-iteration sync. Use only when per-call work >> relay RTT."""
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / iters


def timed_chain(fn, x, iters=20, warmup=2):
    """Chained timing: fn maps x -> same-shape array; each call consumes the
    previous output, so async dispatch through the device relay cannot
    overlap/elide the work being measured."""
    import jax
    y = x
    for _ in range(warmup):
        y = fn(y)
    jax.block_until_ready(y)
    y = x
    t0 = time.perf_counter()
    for _ in range(iters):
        y = fn(y)
    jax.block_until_ready(y)
    return (time.perf_counter() - t0) / iters


def timed_device(fn, x, iters=20, repeats=3):
    """Pure on-device time: ONE dispatch running ``iters`` chained
    applications of ``fn`` inside a lax.fori_loop, reduced to a scalar that
    is READ BACK — on the axon relay ``block_until_ready`` can return
    before execution finishes, so only a value readback is a true sync.
    Min over ``repeats`` (the relay's fixed overhead varies run-to-run);
    use the marginal between two loop lengths to cancel it."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    looped = jax.jit(lambda y: jnp.sum(lax.fori_loop(
        0, iters, lambda i, y: fn(y), y).astype(jnp.float32)))
    float(looped(x))  # compile + run
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(looped(x))
        best = min(best, time.perf_counter() - t0)
    return best / iters


def matmul_roofline(peak_tflops=197.0):
    import jax
    import jax.numpy as jnp
    out = []
    for n in (2048, 4096, 8192):
        try:
            a = jnp.asarray(np.random.default_rng(0).standard_normal(
                (n, n)) * 0.01, jnp.bfloat16)
            b = jnp.asarray(np.random.default_rng(1).standard_normal(
                (n, n)) * 0.01, jnp.bfloat16)
            # marginal cost between two in-device loop lengths — subtracts
            # the relay's fixed ~20ms dispatch+sync overhead exactly.
            # Round-3 verdict weak #2: at small n the per-iter time is
            # ~0.1 ms, so a 50-iteration marginal sat inside timing noise
            # and reported > nominal peak (202.5 > 197 TF/s, impossible).
            # Scale the iteration GAP so the marginal work is >= 200 ms of
            # expected compute at peak — noise then bounds the error at
            # a few percent.
            per_iter_at_peak = 2 * n ** 3 / (peak_tflops * 1e12)
            gap = max(int(0.2 / per_iter_at_peak), 20)
            gap = min(gap, 2400)   # compile-time guard at tiny n
            lo, hi = 5, 5 + gap
            # tanh between iterations defeats XLA's reassociation of the
            # matmul chain into log-depth matrix powers (measured: the pure
            # y@b loop reports >2x nominal peak — it is NOT executing k
            # matmuls)
            body = lambda x, b=b: jnp.tanh(x @ b)  # noqa: E731
            t5 = timed_device(body, a, iters=lo) * lo
            t45 = timed_device(body, a, iters=hi) * hi
            dt = (t45 - t5) / (hi - lo)
            tf = 2 * n ** 3 / dt / 1e12
            rec = {"n": n, "iters": (lo, hi), "ms": round(dt * 1e3, 3),
                   "tflops": round(tf, 1),
                   "fixed_dispatch_ms": round((t5 - lo * dt) * 1e3, 1)}
            if tf > peak_tflops * 1.02:
                # still impossible: record the raw numbers but mark the
                # row invalid rather than publishing a >peak figure
                rec["valid"] = False
                rec["note"] = (f"{tf:.1f} TF/s exceeds nominal peak "
                               f"{peak_tflops}; marginal under-resolved")
            else:
                rec["valid"] = True
            out.append(rec)
        except Exception as e:  # OOM at the largest size is fine
            out.append({"n": n, "error": str(e)[:120]})
    # batched (closer to a transformer step's shape mix); chain via a
    # projection back to the input shape
    for (b, m, k, n) in ((8, 1024, 768, 2048), (8, 2048, 2048, 5504)):
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (b, m, k)) * 0.01, jnp.bfloat16)
        w = jnp.asarray(np.random.default_rng(1).standard_normal(
            (k, n)) * 0.01, jnp.bfloat16)
        w2 = jnp.asarray(np.random.default_rng(2).standard_normal(
            (n, k)) * 0.01, jnp.bfloat16)
        body = lambda x, w=w, w2=w2: jnp.tanh((x @ w) @ w2)  # noqa: E731
        t5 = timed_device(body, x, iters=10) * 10
        t45 = timed_device(body, x, iters=110) * 110
        dt = (t45 - t5) / 100
        tf = 2 * b * m * k * n * 2 / dt / 1e12  # two matmuls per iter
        out.append({"shape": f"[{b},{m},{k}]x[{k},{n}] (x2, chained)",
                    "ms": round(dt * 1e3, 3), "tflops": round(tf, 1)})
    return out


def attention_paths():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.kernels.flash_attention import flash_attention

    res = []
    b, h, d = 4, 12, 64
    for s in (1024, 4096, 8192):
        # kernel layout [b, h, s, d]; chain via the output (same shape)
        q = jnp.asarray(np.random.default_rng(0).standard_normal(
            (b, h, s, d)) * 0.1, jnp.bfloat16)

        def xla_sdpa(q, s=s):
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, q)
            m = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(m, logits, -1e9).astype(jnp.float32)
            p = jax.nn.softmax(logits, -1).astype(q.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", p, q)

        def marginal(fn):
            t3 = timed_device(fn, q, iters=3) * 3
            t15 = timed_device(fn, q, iters=13) * 13
            return (t15 - t3) / 10

        row = {"seq": s}
        try:
            row["xla_ms"] = round(marginal(xla_sdpa) * 1e3, 2)
        except Exception as e:
            row["xla_error"] = str(e)[:80]
        try:
            row["pallas_ms"] = round(marginal(
                lambda q: flash_attention(q, q, q, causal=True)) * 1e3, 2)
        except Exception as e:
            row["pallas_error"] = str(e)[:80]
        res.append(row)

    # 1B-config TRAINING shapes (fwd+bwd, GQA-native k/v, b=1 s=2048):
    # the regime the llama_1b bench runs in. Chained through dq (same
    # shape as q) so the relay cannot elide the backward.
    for (h, hkv, d) in ((32, 4, 64), (16, 4, 128)):
        s = 2048
        q = jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, h, s, d)) * 0.1, jnp.bfloat16)
        kv = jnp.asarray(np.random.default_rng(1).standard_normal(
            (1, hkv, s, d)) * 0.1, jnp.bfloat16)
        g = h // hkv

        def gqa_sdpa(q, kv=kv, g=g, s=s, d=d, hkv=hkv):
            qg = q.reshape(1, hkv, g, s, d)
            logits = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kv) / (d ** 0.5)
            m = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(m, logits, -1e9).astype(jnp.float32)
            p = jax.nn.softmax(logits, -1).astype(q.dtype)
            return jnp.einsum("bhgqk,bhkd->bhgqd", p, kv).reshape(q.shape)

        def fwdbwd(fn):
            return jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32)))

        def marginal2(fn):
            t3 = timed_device(fn, q, iters=3) * 3
            t13 = timed_device(fn, q, iters=13) * 13
            return (t13 - t3) / 10

        row = {"train_shape": f"b1 h{h} hkv{hkv} s{s} d{d}"}
        try:
            row["xla_fwdbwd_ms"] = round(marginal2(fwdbwd(gqa_sdpa)) * 1e3, 2)
        except Exception as e:
            row["xla_error"] = str(e)[:80]
        for bq, bk in ((128, 128), (256, 512), (512, 512)):
            try:
                t = marginal2(fwdbwd(
                    lambda q, bq=bq, bk=bk: flash_attention(
                        q, kv, kv, causal=True, block_q=bq, block_k=bk)))
                row[f"pallas_{bq}x{bk}_fwdbwd_ms"] = round(t * 1e3, 2)
            except Exception as e:
                row[f"pallas_{bq}x{bk}_error"] = str(e)[:80]
        # jax's production splash kernel, GQA-NATIVE (the MQA entry —
        # grouped K/V, no repeat): the same wrapper
        # PADDLE_TPU_ATTN_IMPL=splash engages at the step level
        try:
            from paddle_tpu.kernels import splash_attention
            t = marginal2(fwdbwd(
                lambda q: splash_attention(q, kv, kv, causal=True)))
            row["splash_gqa_fwdbwd_ms"] = round(t * 1e3, 2)
        except Exception as e:
            row["splash_error"] = str(e)[:80]
        res.append(row)
    return res


def step_breakdown():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig
    from paddle_tpu.jit import _Installed, _collect_state
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.core import autograd as _ag

    cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                      intermediate_size=2048, num_hidden_layers=12,
                      num_attention_heads=12, num_key_value_heads=12,
                      max_position_embeddings=1024, loss_chunk_size=2048)
    batch, seq = 8, 1024
    model = LlamaForCausalLM(cfg)
    params, buffers = _collect_state(model)
    state = {**params, **buffers}
    inst = _Installed(state)

    def loss_of(state_arrays, ids):
        with inst:
            inst.install(state_arrays)
            with paddle.amp.auto_cast(enable=True, level="O1",
                                      dtype="bfloat16"):
                return model(Tensor(ids), labels=Tensor(ids))[1]._data

    def fwd(state_arrays, ids):
        with _ag.no_grad():
            return loss_of(state_arrays, ids)

    import jax.numpy as jnp
    from jax import lax
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)))
    arrs = {k: t._data for k, t in state.items()}

    def marginal(once_fn, lo=2, hi=6):
        """In-device loop, chained through the loss so iterations cannot
        overlap; marginal slope removes the fixed dispatch overhead."""
        def loop(k):
            def body(i, ids_c):
                l = once_fn(arrs, ids_c)
                return ids_c + l.astype(jnp.int32) * 0
            f = jax.jit(lambda ids0: jnp.sum(
                lax.fori_loop(0, k, body, ids0)))
            int(f(ids))  # compile + run (readback = true sync on the relay)
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                int(f(ids))
                best = min(best, time.perf_counter() - t0)
            return best
        return (loop(hi) - loop(lo)) / (hi - lo)

    t_fwd = marginal(lambda a, i: fwd(a, i))

    def fwd_bwd(state_arrays, ids):
        p_keys = [k for k in state_arrays if not k.startswith("b:")]

        def pure(p_arrays):
            merged = {**state_arrays, **p_arrays}
            with _ag.no_grad():
                return loss_of(merged, ids)
        l, g = jax.value_and_grad(pure)({k: state_arrays[k] for k in p_keys})
        return l, g

    def fwd_bwd_scalar(a, i):
        l, g = fwd_bwd(a, i)
        # fold EVERY grad leaf in so no part of the backward is dead code
        tot = sum(jnp.sum(v).astype(jnp.float32) for v in g.values())
        return l + tot * 0

    t_fwd_bwd = marginal(fwd_bwd_scalar)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(i):
        with paddle.amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            return model(i, labels=i)[1]
    step = paddle.jit.TrainStep(model, loss_fn, opt)
    tens = Tensor(ids)
    _ = float(step(tens).numpy())
    t0 = time.perf_counter()
    for _ in range(10):
        loss = step(tens)
    float(loss.numpy())
    t_step = (time.perf_counter() - t0) / 10

    flops_tok = model.flops_per_token(seq)
    toks = batch * seq
    return {
        "config": "llama_125m b=8 s=1024 bf16-O1",
        "flops_per_token_fwd_bwd": flops_tok,
        "fwd_ms": round(t_fwd * 1e3, 2),
        "fwd_bwd_ms": round(t_fwd_bwd * 1e3, 2),
        "full_step_ms": round(t_step * 1e3, 2),
        "optimizer_overhead_ms": round((t_step - t_fwd_bwd) * 1e3, 2),
        "achieved_model_tflops": round(toks * flops_tok / t_step / 1e12, 1),
        "tokens_per_sec": round(toks / t_step, 1),
    }


def main():
    import jax
    import jax.numpy as jnp
    try:  # repeated audit runs skip recompiles
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/paddle_tpu_audit_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    dev = jax.devices()[0]
    out = {"device": getattr(dev, "device_kind", str(dev)),
           "platform": dev.platform}
    # dispatch+sync round-trip through the device relay: the floor any
    # per-iteration-synced measurement carries
    noop = jax.jit(lambda x: x + 1)
    out["rtt_ms"] = round(timed(noop, jnp.zeros(()), iters=20) * 1e3, 3)
    print("rtt_ms:", out["rtt_ms"], flush=True)
    path = os.path.join(REPO, "docs", "PERF_AUDIT.json")
    if os.path.exists(path):  # sectioned runs merge into one artifact
        try:
            prev = json.load(open(path))
            prev.update(out)
            out = prev
        except Exception:
            pass
    sections = [s for s in sys.argv[1:] if not s.startswith("-")] \
        or ["matmul", "attention", "step"]
    if "matmul" in sections:
        print("== matmul roofline ==", flush=True)
        out["matmul_roofline"] = matmul_roofline()
        print(json.dumps(out["matmul_roofline"], indent=1), flush=True)
    if "attention" in sections:
        print("== attention paths ==", flush=True)
        out["attention"] = attention_paths()
        print(json.dumps(out["attention"], indent=1), flush=True)
    if "step" in sections:
        print("== step breakdown ==", flush=True)
        out["step"] = step_breakdown()
        print(json.dumps(out["step"], indent=1), flush=True)
    os.makedirs(os.path.join(REPO, "docs"), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote docs/PERF_AUDIT.json")


if __name__ == "__main__":
    main()
