"""CPU-tier proxy perf bench: chip-free regression gate over the
counted perf surfaces.

The flagship bench (bench.py) needs a live chip for tok/s and MFU — and
the chip pool can wedge for days (BENCH_r03-r05 are stale fallbacks).
This harness runs the measurements that DON'T need a chip and are
(near-)deterministic counts rather than timings:

- ``decode_compiles`` — ragged-step executables across a mixed serving
  wave (must stay 1: a jump is shape-dependent recompilation);
- ``host_dispatches_per_token`` — burst-mode serving dispatches per
  generated token (the on-device token loop's O(1)-per-burst contract;
  forcing the per-token path drives it toward >= 1);
- ``opt_dispatches_per_step`` — fused-optimizer dispatch count;
- ``host_syncs_per_epoch`` — async-pipeline blocking fetch rounds;
- ``fwd_jaxpr_eqns_scan`` / ``fwd_jaxpr_eqn_growth`` — trace size of the
  scanned forward and its growth with depth (must be 0);
- ``kv_bytes_per_token_fp32`` / ``_int8`` — exact KV pool byte
  accounting at a reference geometry;
- ``prefix_cache_hit_rate`` / ``shared_page_fraction`` — prefix-cache
  effectiveness over the shared-prefix wave (higher is better);
- ``cluster_goodput_fraction`` / ``cluster_retries`` /
  ``cluster_ttft_p99_s`` / ``cluster_unresolved`` — fleet robustness
  under a scripted kill-and-recover run (serving/cluster.py on the
  loadgen virtual clock; ``--no-retry`` is the injected regression);
- ``hlo_train_*`` / ``hlo_serving_*`` — fusion/kernel counts and
  bytes-touched-per-fused-region of the jitted TrainStep and the
  ragged serving step (jit/hlo_forensics.py; a defused hot region is
  silent 2x HBM traffic on chip — ``--defuse`` is the injected
  regression);
- ``trace_deterministic`` / ``trace_span_count`` /
  ``trace_decode_compiles`` — the request-tracing layer's contracts:
  byte-identical exports per seed and zero added step executables
  (serving/tracing.py);
- ``disagg_*`` — disaggregated prefill/decode serving contracts
  (serving/fabric.py + ClusterEngine roles): token identity vs a
  colocated fleet, KV pages actually moved over the fabric, fleet
  prefix hit rate with a crashed publisher, transfer stall fraction,
  byte-reproducible fleet reports, and the TTFT-p99 ratio vs
  colocated under a long-prompt flood (``--colocated`` is the
  injected regression);
- ``telemetry_*`` — the fleet time-series/SLO layer's contracts
  (paddle_tpu.telemetry): byte-identical series + alert-timeline
  exports per seed, a pinned scrape count, the seeded slowdown fault
  firing AND resolving its burn-rate alert (``--no-burn-alerts`` is
  the injected regression), and zero added step executables;
- ``multitenant_*`` — the multi-tenant serving economy's contracts
  (paddle_tpu.tenancy): noisy-neighbor p99 TTFT isolation under
  weighted-fair admission, exact quota-shed counts, byte-reproducible
  tenant reports, mixed-batch LoRA token identity over the int8 base,
  and adapter hot-swap with zero recompiles (``--no-fairness`` is the
  injected regression: bare FIFO over the same flood);
- ``pipeline_*`` — the pipeline-parallel stage axis's contracts
  (distributed/gspmd.py ``pp=K`` presets + the in-jit 1F1B microbatch
  loop): loss parity <= 1e-6 vs the single-device run for pp=2 and
  dp=2,pp=2, the stage-ring collective-permute count pinned both ways
  at its structural value, max-stage param byte fraction, the analytic
  bubble fraction (K-1)/(M+K-1) cross-checked against the schedule
  layout, and ONE staged TrainStep executable (``--no-pipeline`` is
  the injected regression: pp=1 gradient accumulation at the same
  microbatch count);
- ``mk_*`` — the whole-model decode megakernel's launch-collapse
  contracts (kernels/decode_megakernel.py ``fused_decode_model``): the
  decoder layer body appears ONCE in the ragged step's program
  (launches/token == 1.0 regardless of depth) and once per burst
  executable (1/burst_tokens), tokens stay bitwise identical to layer
  scope, and the compiled ragged step's fusion/kernel counts are
  pinned (``--per-layer`` is the injected regression: scope forced
  back to layer, launches/token rise to num_layers).

Each metric gates against a checked-in per-backend baseline
(tools/proxy_bench_baseline.json) with a direction and tolerance from
``GATES`` — a regression fails with rc 1, parity passes with rc 0, so
perf regressions surface in CI without a chip (docs/BENCH.md compares
these proxies with the chip metrics they predict).

Usage:
  python -m tools.proxy_bench                     # run, print JSON
  python -m tools.proxy_bench --record            # (re)record baseline
  python -m tools.proxy_bench --compare tools/proxy_bench_baseline.json
  python -m tools.proxy_bench --probes serving,jaxpr --compare ...

The probes themselves live in tools/bench_probes.py and are shared with
bench.py, which spreads the same fields into its flagship artifact.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the gspmd probe needs a multi-device mesh; force the 8-device
# host-CPU stand-in (the same environment tests/conftest.py pins for
# the whole suite — XLA parses XLA_FLAGS at backend creation, so this
# works as long as no device has been touched yet; on a real TPU the
# flag only affects the host platform)
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))

BASELINE_PATH = os.path.join(REPO, "tools", "proxy_bench_baseline.json")

PROBES = ("serving", "spec", "gspmd", "cluster", "optimizer",
          "input_pipeline", "pipeline",
          "jaxpr", "accounting", "fusion", "tracing", "telemetry",
          "persist", "kvtier", "disagg", "multitenant", "megakernel")


class Gate:
    """Direction-aware tolerance: ``worse`` names the failing direction.

    higher-is-worse: fail when cur > base * (1 + rel) + abs
    lower-is-worse:  fail when cur < base * (1 - rel) - abs
    different-is-worse: fail when cur != base (exact two-sided pin —
    for counts where a DROP is as suspicious as a rise, e.g. the GSPMD
    collective mix: a rule-table miss that replicates params LOWERS the
    all-gather count).
    Counts gate tightly (rel 0, small abs); ratios get slack for
    environment drift. A None measurement where the baseline has a
    number is always a failure — a probe that stopped measuring is a
    silent coverage loss, not a pass.
    """

    def __init__(self, worse="higher", rel=0.0, abs_=0.0):
        assert worse in ("higher", "lower", "different")
        self.worse = worse
        self.rel = rel
        self.abs_ = abs_

    def bad(self, cur, base) -> bool:
        if self.worse == "different":
            return cur != base
        if self.worse == "higher":
            return cur > base * (1.0 + self.rel) + self.abs_
        return cur < base * (1.0 - self.rel) - self.abs_

    def bound(self, base) -> float:
        if self.worse in ("higher", "different"):
            return base * (1.0 + self.rel) + self.abs_
        return base * (1.0 - self.rel) - self.abs_


GATES = {
    "decode_compiles":          Gate("higher", 0.0, 0.0),
    "host_dispatches_per_token": Gate("higher", 0.20, 0.01),
    "opt_dispatches_per_step":  Gate("higher", 0.0, 2.0),
    "host_syncs_per_epoch":     Gate("higher", 0.0, 2.0),
    "fwd_jaxpr_eqns_scan":      Gate("higher", 0.10, 0.0),
    "fwd_jaxpr_eqn_growth":     Gate("higher", 0.0, 0.0),
    "kv_bytes_per_token_fp32":  Gate("higher", 0.0, 0.0),
    "kv_bytes_per_token_int8":  Gate("higher", 0.0, 0.0),
    "prefix_cache_hit_rate":    Gate("lower", 0.0, 0.10),
    "shared_page_fraction":     Gate("lower", 0.0, 0.10),
    # speculative decoding: launches per committed token must stay well
    # under 1 (disabling the draft drives it to exactly 1.0 — the
    # injected regression), acceptance must not collapse, and the spec
    # rounds must keep riding the ONE ragged executable
    "spec_target_steps_per_token": Gate("higher", 0.20, 0.02),
    "spec_accept_rate":         Gate("lower", 0.0, 0.15),
    "spec_decode_compiles":     Gate("higher", 0.0, 0.0),
    # GSPMD sharding: compile counts stay 1 under the mesh, the
    # collective mix of the tp=2 x dp=4 step is pinned exactly BOTH
    # ways (more collectives = partitioner drift; FEWER = the rule
    # table stopped matching and params silently replicated), and
    # per-device sharded KV bytes/token is exact accounting — forcing
    # the dp-only regime (--dp-only) doubles it and must fail the gate
    "gspmd_train_compiles":     Gate("higher", 0.0, 0.0),
    "gspmd_allreduce_count":    Gate("different"),
    "gspmd_allgather_count":    Gate("different"),
    "gspmd_serving_decode_compiles": Gate("higher", 0.0, 0.0),
    "gspmd_sharded_kv_bytes_per_token": Gate("higher", 0.0, 0.0),
    # cluster robustness (scripted kill-and-recover on the virtual
    # clock — every field is a deterministic count/fraction): fleet
    # goodput must not collapse (disabling retries via --no-retry
    # converts the killed replica's requeues into sheds and MUST fail
    # this gate), the requeue count is pinned exactly (a drift means
    # fault timing or routing changed — re-record deliberately), p99
    # TTFT gets modest slack, and unresolved requests are forbidden
    # outright (retry exhaustion must shed, never hang)
    "cluster_goodput_fraction": Gate("lower", 0.0, 0.05),
    "cluster_retries":          Gate("different"),
    "cluster_ttft_p99_s":       Gate("higher", 0.25, 0.02),
    "cluster_unresolved":       Gate("higher", 0.0, 0.0),
    # HLO fusion forensics (jit/hlo_forensics.py via probe_hlo_fusion):
    # fusion/kernel counts and bytes-touched-per-fused-region of the
    # jitted TrainStep and the ragged serving step are deterministic
    # for a pinned jaxlib, and MORE of any of them means a hot region
    # defused — silent 2x HBM traffic on chip. Exact one-sided pins:
    # an improvement (fewer kernels) passes, a regression fails.
    # --defuse (FLAGS_fusion_probe_barrier) is the injected regression
    # splitting the ragged layer's fused region; the serving gates must
    # catch it.
    "hlo_train_fusions":        Gate("higher", 0.0, 0.0),
    "hlo_train_kernels":        Gate("higher", 0.0, 0.0),
    "hlo_serving_fusions":      Gate("higher", 0.0, 0.0),
    "hlo_serving_kernels":      Gate("higher", 0.0, 0.0),
    "hlo_serving_fusion_bytes": Gate("higher", 0.0, 0.0),
    # request tracing (serving/tracing.py via probe_tracing): the
    # byte-identical-export contract is exact (0 = a wall-clock read or
    # hash-ordered container poisoned the span path), the span count is
    # pinned (schema/lifecycle-hook drift must be re-recorded
    # deliberately), and tracing must add zero step executables.
    "trace_deterministic":      Gate("lower", 0.0, 0.0),
    "trace_span_count":         Gate("different"),
    "trace_decode_compiles":    Gate("higher", 0.0, 0.0),
    # fleet telemetry (paddle_tpu.telemetry via probe_telemetry): the
    # full time-series/alert export must be byte-identical per seed,
    # the scrape count is pinned (cadence/run-length drift must be
    # re-recorded deliberately), the seeded slowdown fault must FIRE
    # and later RESOLVE the burn-rate alert (both pinned exactly —
    # --no-burn-alerts drops the rules, both read 0, and these gates
    # must catch it), and scraping must add zero step executables.
    "telemetry_deterministic":  Gate("lower", 0.0, 0.0),
    "telemetry_scrape_samples": Gate("different"),
    "telemetry_alerts_fired":   Gate("different"),
    "telemetry_alerts_resolved": Gate("different"),
    "telemetry_decode_compiles": Gate("higher", 0.0, 0.0),
    # crash-consistent persistence (io/persist.py via probe_persistence):
    # the killed-and-resumed loss trajectory must stay BIT-identical to
    # the unkilled run (0 = resume diverged or restored stale state),
    # restores must not fall back (a fallback means a stored version
    # failed verification), and the warm-restarted engine must serve
    # its pinned-prefix hit (0 = the store restored nothing and the
    # cohort prompt re-prefilled). --corrupt-checkpoint flips a byte in
    # every stored version: all three gates must catch it.
    "persist_resume_identical":  Gate("lower", 0.0, 0.0),
    "persist_restore_fallbacks": Gate("higher", 0.0, 0.0),
    "persist_warm_prefix_hits":  Gate("lower", 0.0, 0.0),
    # two-tier KV cache (serving/kv_tier.py via probe_kv_tiering): an
    # engine whose HBM page budget is strictly smaller than the seeded
    # workload's working set (long-context lane included) must serve it
    # TOKEN-IDENTICALLY to an all-HBM oracle, actually exercising the
    # tiers (spill/prefetch-hit counts pinned exactly — a drift means
    # the spill policy or admission math changed; re-record
    # deliberately), with ZERO steady-state prefetch stalls (every
    # restore staged a full round ahead of the cursor) and a
    # byte-reproducible loadgen report per seed. --no-prefetch disables
    # the cursor-ahead staging: every restore becomes a counted stall,
    # hits drop to 0, and these gates must catch it.
    "kv_tier_token_identical":   Gate("lower", 0.0, 0.0),
    "kv_tier_spills":            Gate("different"),
    "kv_tier_prefetch_hits":     Gate("different"),
    "kv_tier_stall_fraction":    Gate("higher", 0.0, 0.0),
    "kv_tier_deterministic":     Gate("lower", 0.0, 0.0),
    # disaggregated prefill/decode serving (serving/fabric.py via
    # probe_disagg): the disagg fleet must serve the seeded
    # shared-prefix workload (publisher crash included) token-
    # identically to a colocated fleet, actually move KV pages over
    # the fabric (the count is pinned exactly — a drift means the
    # handoff policy or router changed; re-record deliberately), hit
    # the fleet prefix cache cross-replica, keep transfer back-
    # pressure stalls at 0, reproduce the cluster report byte for
    # byte, and beat the colocated fleet's TTFT p99 on the long-prompt
    # flood (the ratio must stay well under 1). --colocated serves
    # both scenarios with roles=None: pages drop to 0, the hit rate
    # reads 0, the ratio collapses to ~1 — those three gates must all
    # catch it.
    "disagg_token_identical":    Gate("lower", 0.0, 0.0),
    "disagg_kv_pages_transferred": Gate("different"),
    "disagg_fleet_prefix_hit_rate": Gate("lower", 0.0, 0.0),
    "disagg_transfer_stall_fraction": Gate("higher", 0.0, 0.0),
    "disagg_ttft_ratio_vs_colocated": Gate("higher", 0.25, 0.05),
    "disagg_deterministic":      Gate("lower", 0.0, 0.0),
    # multi-tenant serving economy (paddle_tpu.tenancy via
    # probe_multitenant): the weighted-fair scheduler must hold the
    # good tenant's p99 TTFT flat while the metered noisy tenant
    # floods — the isolation ratio (good p99 / noisy p99, virtual
    # clock, deterministic) stays far below 1 and the abuser's
    # overflow is shed by quota (count pinned exactly per seed — a
    # drift means admission or refill math changed; re-record
    # deliberately). The tenant-annotated loadgen report must be
    # byte-reproducible, the mixed LoRA/base batch must decode the
    # base row bit-identically to a no-adapter engine over the int8
    # base, and adapter evict + hot-add must leave the ONE ragged
    # decode executable alone. --no-fairness serves the same flood
    # FIFO with no policy: sheds read 0, good's p99 blows out behind
    # the abuser's backlog, the isolation ratio collapses toward 1 —
    # the first three gates must all catch it.
    "multitenant_good_ttft_p99_s": Gate("higher", 0.25, 0.02),
    "multitenant_isolation_ratio": Gate("higher", 0.25, 0.05),
    "multitenant_quota_shed":    Gate("different"),
    "multitenant_deterministic": Gate("lower", 0.0, 0.0),
    "multitenant_mixed_batch_identical": Gate("lower", 0.0, 0.0),
    "multitenant_hot_swap_compiles": Gate("higher", 0.0, 0.0),
    # whole-model decode megakernel (kernels/decode_megakernel.py
    # fused_decode_model via probe_megakernel): the decoder layer body
    # must appear ONCE in the ragged step's program (launches/token
    # == 1.0 regardless of depth) and once in the burst executable
    # (1/burst_tokens per token), the engine must actually be at model
    # scope, tokens must stay bitwise identical to layer scope, and
    # the COMPILED ragged step's fusion/kernel counts are pinned
    # one-sided (the scanned prologue/epilogue chains appear once, not
    # once per layer). --per-layer forces the measured engine back to
    # layer scope: scope reads 0, launches/token rise to num_layers,
    # the compiled counts rise — five of the six gates must catch it.
    # pipeline-parallel stage axis (distributed/gspmd.py + the in-jit
    # 1F1B microbatch loop via probe_pipeline): pp=2 (and dp=2,pp=2)
    # training must stay loss-identical (<=1e-6) to the single-device
    # run — parity is a 0/1 verdict and 0 is an unconditional failure.
    # The stage-ring collective-permute count is structurally pinned
    # BOTH ways (5: forward shift + output collect + their two scan
    # transposes + the cotangent inject — more means the partitioner
    # started bouncing activations, fewer means the ring dissolved into
    # all-gathers), the max-stage param byte fraction must not rise
    # (a stage silently owning more than total/K + embed/head slack is
    # lost pipeline memory scaling), the analytic bubble fraction
    # (K-1)/(M+K-1) is cross-checked against the 1F1B schedule layout
    # inside the probe and pinned here, and the staged TrainStep must
    # still compile exactly once. --no-pipeline serves the same
    # microbatch count as pp=1 gradient accumulation: rings read 0,
    # the stage fraction reads 1.0, the bubble reads 0 — four gates
    # must catch it.
    "pipeline_loss_parity":      Gate("lower", 0.0, 0.0),
    "pipeline_ring_permutes":    Gate("different"),
    "pipeline_dp_ring_permutes": Gate("different"),
    "pipeline_max_stage_param_fraction": Gate("higher", 0.0, 0.0),
    "pipeline_bubble_fraction":  Gate("different"),
    "pipeline_train_compiles":   Gate("higher", 0.0, 0.0),
    "mk_model_scope":            Gate("lower", 0.0, 0.0),
    "mk_launches_per_token":     Gate("higher", 0.0, 0.0),
    "mk_burst_launches_per_token": Gate("higher", 0.0, 0.0),
    "mk_token_identity":         Gate("lower", 0.0, 0.0),
    "mk_serving_fusions":        Gate("higher", 0.0, 0.0),
    "mk_serving_kernels":        Gate("higher", 0.0, 0.0),
    # fused ragged prefill (kernels/prefill_megakernel.py via
    # probe_megakernel's mk_prefill_* family): the fused engine's
    # COMPILED ragged step is pinned one-sided strictly BELOW the
    # unfused mk_serving_* floor (the fused body drops the ragged
    # rank loops and fuses the projection chain — any rise is a
    # defusion), tokens must stay bitwise identical to the unfused
    # engine, launches-per-chunk must not rise (the ONE fixed-shape
    # step covers every chunk it packs), and the long-prompt-flood
    # TTFT under the launch-cost virtual-clock model must keep its
    # headline improvement (ratio vs unfused < 1; throughput must not
    # drop; decode progress pinned exactly — a flood that starves
    # decode is not a TTFT win). --per-layer-prefill builds the
    # measured engine UNFUSED: compiled counts climb to the floor,
    # the ratio reads 1.0, throughput drops — the gates must catch it.
    "mk_prefill_fusions":        Gate("higher", 0.0, 0.0),
    "mk_prefill_kernels":        Gate("higher", 0.0, 0.0),
    "mk_prefill_token_identity": Gate("lower", 0.0, 0.0),
    "mk_prefill_launches_per_chunk": Gate("higher", 0.0, 0.0),
    "mk_prefill_ttft_p99_s":     Gate("higher", 0.0, 0.0),
    "mk_prefill_ttft_ratio_vs_unfused": Gate("higher", 0.0, 0.0),
    "mk_prefill_tokens_per_s":   Gate("lower", 0.0, 0.0),
    "mk_prefill_decode_tokens":  Gate("different"),
}


def collect(probes=PROBES, burst_tokens=8, spec_tokens=4,
            gspmd_dp_only=False, cluster_retry_budget=2,
            fusion_defuse=False, telemetry_burn_alerts=True,
            persist_corrupt=False, kvtier_prefetch=True,
            disagg_colocated=False, multitenant_fairness=True,
            megakernel_per_layer=False, pipeline_no_pp=False,
            megakernel_per_layer_prefill=False) -> dict:
    """Run the selected probes; returns {backend, probes, metrics}.

    ``burst_tokens=1`` forces the serving engine's per-token dispatch
    path — the deliberate-regression hook the compare-mode test uses to
    prove the ``host_dispatches_per_token`` gate actually fires.
    ``spec_tokens=0`` disables the speculative draft the same way —
    target steps per committed token then reads exactly 1.0 and the
    ``spec_target_steps_per_token`` gate must catch it.
    ``gspmd_dp_only=True`` forces the data-parallel-only regime (no
    model axis) — per-device sharded KV bytes/token double and the
    ``gspmd_sharded_kv_bytes_per_token`` gate must catch it.
    ``cluster_retry_budget=0`` (--no-retry) disables cross-replica
    requeue in the kill-and-recover cluster probe — the killed
    replica's in-flight requests shed instead of retrying, fleet
    goodput collapses, and the ``cluster_goodput_fraction`` gate must
    catch it.
    ``fusion_defuse=True`` (--defuse) sets FLAGS_fusion_probe_barrier,
    splitting the ragged serving layer's hot fused region at trace time
    — fusion/kernel counts and fused-region bytes rise and the
    ``hlo_serving_*`` gates must catch it.
    ``telemetry_burn_alerts=False`` (--no-burn-alerts) drops the burn-
    rate rules from the telemetry probe's scraper — the seeded
    slowdown fault then fires (and resolves) nothing, both alert
    counts read 0, and the ``telemetry_alerts_*`` gates must catch it.
    ``persist_corrupt=True`` (--corrupt-checkpoint) flips a byte in
    every version of the probe's stored training checkpoint AND prefix
    store — resume identity breaks, restores fall back, warm hits
    vanish, and the ``persist_*`` gates must catch all of it.
    ``kvtier_prefetch=False`` (--no-prefetch) disables the two-tier KV
    probe's cursor-ahead staging — every parked-sequence restore
    becomes a counted stall and prefetch hits drop to 0; the
    ``kv_tier_stall_fraction`` and ``kv_tier_prefetch_hits`` gates
    must catch it.
    ``disagg_colocated=True`` (--colocated) serves the disagg probe's
    scenarios with ``roles=None`` — zero KV pages move over the
    fabric, the fleet prefix cache never hits, and the TTFT ratio
    collapses to ~1; the ``disagg_kv_pages_transferred``,
    ``disagg_fleet_prefix_hit_rate``, and
    ``disagg_ttft_ratio_vs_colocated`` gates must catch it.
    ``multitenant_fairness=False`` (--no-fairness) serves the
    multitenant probe's noisy-neighbor flood with NO tenant policy
    (bare FIFO): quota sheds read 0, the good tenant's p99 TTFT blows
    out behind the abuser's backlog, and the isolation ratio collapses
    toward 1; the ``multitenant_quota_shed``,
    ``multitenant_good_ttft_p99_s``, and
    ``multitenant_isolation_ratio`` gates must all catch it.
    ``pipeline_no_pp=True`` (--no-pipeline) replaces the pipeline-
    parallel probe's staged runs with pp=1 data-parallel runs at the
    SAME microbatch count (gradient accumulation): the pipeline ring
    permutes read 0, the max-stage param fraction reads 1.0 (no stage
    owns less than everything), and the bubble fraction reads 0 — the
    ``pipeline_ring_permutes``/``pipeline_dp_ring_permutes``/
    ``pipeline_max_stage_param_fraction``/``pipeline_bubble_fraction``
    gates must all catch it.
    ``megakernel_per_layer=True`` (--per-layer) forces the megakernel
    probe's measured engine back to layer scope: ``mk_model_scope``
    reads 0, launches per token rise from 1.0 to num_layers, the
    compiled ragged step's fusion/kernel counts rise — the
    ``mk_model_scope``/``mk_launches_per_token``/
    ``mk_burst_launches_per_token``/``mk_serving_*`` gates must all
    catch it.
    ``megakernel_per_layer_prefill=True`` (--per-layer-prefill) builds
    the fused-prefill measurement's engine UNFUSED: the compiled
    ragged-step counts climb back to the unfused ``mk_serving_*``
    floor, the flood TTFT ratio reads 1.0, and flood throughput drops
    — the ``mk_prefill_fusions``/``mk_prefill_kernels``/
    ``mk_prefill_ttft_p99_s``/``mk_prefill_ttft_ratio_vs_unfused``/
    ``mk_prefill_tokens_per_s`` gates must all catch it.
    """
    import jax
    import paddle_tpu as paddle
    from tools.bench_probes import (probe_cluster, probe_disagg,
                                    probe_gspmd,
                                    probe_hlo_fusion,
                                    probe_input_pipeline, probe_jaxpr,
                                    probe_pipeline,
                                    probe_kv_accounting,
                                    probe_megakernel,
                                    probe_multitenant,
                                    probe_opt_dispatches,
                                    probe_kv_tiering,
                                    probe_persistence, probe_serving,
                                    probe_spec_decode, probe_telemetry,
                                    probe_tracing)
    dev = jax.devices()[0]
    backend = dev.platform if dev.platform == "cpu" else \
        getattr(dev, "device_kind", "tpu").replace(" ", "-").lower()
    metrics: dict = {}
    errors: dict = {}

    def _take(blob, keys):
        for k in keys:
            metrics[k] = blob.get(k)
        for k, v in blob.items():
            if k.endswith("_probe_error"):
                errors[k] = v

    if "serving" in probes:
        _take(probe_serving(paddle, burst_tokens=burst_tokens),
              ("decode_compiles", "host_dispatches_per_token",
               "prefix_cache_hit_rate", "shared_page_fraction"))
    if "spec" in probes:
        _take(probe_spec_decode(paddle, spec_tokens=spec_tokens),
              ("spec_target_steps_per_token", "spec_accept_rate",
               "spec_decode_compiles"))
    if "gspmd" in probes:
        _take(probe_gspmd(paddle, dp_only=gspmd_dp_only),
              ("gspmd_train_compiles", "gspmd_allreduce_count",
               "gspmd_allgather_count", "gspmd_serving_decode_compiles",
               "gspmd_sharded_kv_bytes_per_token"))
    if "cluster" in probes:
        _take(probe_cluster(paddle, retry_budget=cluster_retry_budget),
              ("cluster_goodput_fraction", "cluster_retries",
               "cluster_ttft_p99_s", "cluster_unresolved"))
    if "optimizer" in probes:
        _take(probe_opt_dispatches(paddle), ("opt_dispatches_per_step",))
    if "input_pipeline" in probes:
        _take(probe_input_pipeline(paddle), ("host_syncs_per_epoch",))
    if "pipeline" in probes:
        _take(probe_pipeline(paddle, no_pipeline=pipeline_no_pp),
              ("pipeline_loss_parity", "pipeline_ring_permutes",
               "pipeline_dp_ring_permutes",
               "pipeline_max_stage_param_fraction",
               "pipeline_bubble_fraction", "pipeline_train_compiles"))
    if "jaxpr" in probes:
        _take(probe_jaxpr(paddle),
              ("fwd_jaxpr_eqns_scan", "fwd_jaxpr_eqn_growth"))
    if "accounting" in probes:
        _take(probe_kv_accounting(),
              ("kv_bytes_per_token_fp32", "kv_bytes_per_token_int8"))
    if "fusion" in probes:
        _take(probe_hlo_fusion(paddle, defuse=fusion_defuse),
              ("hlo_train_fusions", "hlo_train_kernels",
               "hlo_serving_fusions", "hlo_serving_kernels",
               "hlo_serving_fusion_bytes"))
    if "tracing" in probes:
        _take(probe_tracing(paddle),
              ("trace_deterministic", "trace_span_count",
               "trace_decode_compiles"))
    if "telemetry" in probes:
        _take(probe_telemetry(paddle, burn_alerts=telemetry_burn_alerts),
              ("telemetry_deterministic", "telemetry_scrape_samples",
               "telemetry_alerts_fired", "telemetry_alerts_resolved",
               "telemetry_decode_compiles"))
    if "persist" in probes:
        # the save/restore ms timings ride bench.py's artifact only —
        # wall-clock noise has no place in an exact-count gate set
        _take(probe_persistence(paddle, corrupt=persist_corrupt),
              ("persist_resume_identical", "persist_restore_fallbacks",
               "persist_warm_prefix_hits"))
    if "kvtier" in probes:
        # hbm/host page counts ride bench.py's artifact only — the
        # five gated fields are the deterministic contract
        _take(probe_kv_tiering(paddle, prefetch=kvtier_prefetch),
              ("kv_tier_token_identical", "kv_tier_spills",
               "kv_tier_prefetch_hits", "kv_tier_stall_fraction",
               "kv_tier_deterministic"))
    if "disagg" in probes:
        # the absolute TTFT p99s ride bench.py's artifact only — the
        # gated contract is the identity/pages/hit-rate/stall/ratio/
        # determinism sextet
        _take(probe_disagg(paddle, colocated=disagg_colocated),
              ("disagg_token_identical", "disagg_kv_pages_transferred",
               "disagg_fleet_prefix_hit_rate",
               "disagg_transfer_stall_fraction",
               "disagg_ttft_ratio_vs_colocated",
               "disagg_deterministic"))
    if "multitenant" in probes:
        _take(probe_multitenant(paddle, fairness=multitenant_fairness),
              ("multitenant_good_ttft_p99_s",
               "multitenant_isolation_ratio", "multitenant_quota_shed",
               "multitenant_deterministic",
               "multitenant_mixed_batch_identical",
               "multitenant_hot_swap_compiles"))
    if "megakernel" in probes:
        _take(probe_megakernel(
                  paddle, per_layer=megakernel_per_layer,
                  per_layer_prefill=megakernel_per_layer_prefill),
              ("mk_model_scope", "mk_launches_per_token",
               "mk_burst_launches_per_token", "mk_token_identity",
               "mk_serving_fusions", "mk_serving_kernels",
               "mk_prefill_fusions", "mk_prefill_kernels",
               "mk_prefill_token_identity",
               "mk_prefill_launches_per_chunk",
               "mk_prefill_ttft_p99_s",
               "mk_prefill_ttft_ratio_vs_unfused",
               "mk_prefill_tokens_per_s", "mk_prefill_decode_tokens"))
    out = {"backend": backend, "probes": sorted(probes),
           "metrics": metrics}
    if errors:
        out["probe_errors"] = errors
    return out


def gate(current, baseline, *, require_all=True):
    """Compare a collection against a baseline blob of the same backend.

    Returns (failures, report_str): failures is [(metric, reason)].
    ``require_all=False`` skips baseline metrics absent from the current
    run (partial --probes collections); full runs treat a missing metric
    as a failure — silent coverage loss must not read as a pass.
    """
    failures, lines = [], []
    base = baseline.get("metrics", {})
    for name, cur in sorted(current.get("metrics", {}).items()):
        ref = base.get(name)
        g = GATES.get(name, Gate("higher", 0.25, 0.0))
        if ref is None:
            lines.append(f"  {name:<28} {cur!s:>12}   (new, no baseline)")
            continue
        if cur is None:
            lines.append(f"  {name:<28} {'null':>12}   baseline "
                         f"{ref:>10}   << PROBE BROKE")
            failures.append((name, "measurement is null"))
            continue
        bad = g.bad(cur, ref)
        flag = "  << REGRESSION" if bad else ""
        op = {"higher": ">", "lower": "<", "different": "!="}[g.worse]
        lines.append(
            f"  {name:<28} {cur:>12.4f}   baseline {ref:>10.4f}   "
            f"(fail {op} {g.bound(ref):.4f}){flag}")
        if bad:
            failures.append(
                (name, f"{cur} vs baseline {ref} "
                       f"(worse={g.worse}, bound {g.bound(ref):.4f})"))
    missing = sorted(set(base) - set(current.get("metrics", {})))
    if require_all:
        for name in missing:
            lines.append(f"  {name:<28} MISSING from current run")
            failures.append((name, "missing from current run"))
    return failures, "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="CPU-tier proxy perf bench (counts, not timings)")
    ap.add_argument("--record", action="store_true",
                    help="write the baseline for this backend")
    ap.add_argument("--compare", metavar="BASELINE", nargs="?",
                    const=BASELINE_PATH, default=None,
                    help="gate against a baseline file (default: "
                         "tools/proxy_bench_baseline.json)")
    ap.add_argument("--json", action="store_true",
                    help="print the collection JSON only")
    ap.add_argument("--probes", default=",".join(PROBES),
                    help=f"comma list from {PROBES}")
    ap.add_argument("--burst-tokens", type=int, default=8,
                    help="serving probe burst length (1 forces the "
                         "per-token dispatch path)")
    ap.add_argument("--spec-tokens", type=int, default=4,
                    help="spec probe draft length (0 disables the draft "
                         "— one target launch per token again)")
    ap.add_argument("--dp-only", action="store_true",
                    help="force the gspmd probe's data-parallel-only "
                         "regime (no model axis — per-device sharded KV "
                         "bytes/token double; the injected regression)")
    ap.add_argument("--no-retry", action="store_true",
                    help="zero the cluster probe's retry budget: the "
                         "killed replica's requests shed instead of "
                         "requeueing, fleet goodput collapses (the "
                         "injected regression)")
    ap.add_argument("--defuse", action="store_true",
                    help="set FLAGS_fusion_probe_barrier in the fusion "
                         "probe: an optimization barrier splits the "
                         "ragged layer's hot fused region, fusion/"
                         "kernel counts rise (the injected regression)")
    ap.add_argument("--no-burn-alerts", action="store_true",
                    help="drop the burn-rate rules from the telemetry "
                         "probe's scraper: the seeded slowdown fault "
                         "fires no alert, fired/resolved counts read 0 "
                         "(the injected regression)")
    ap.add_argument("--corrupt-checkpoint", action="store_true",
                    help="flip a byte in every version of the "
                         "persistence probe's stored checkpoint and "
                         "prefix store: resume identity breaks and "
                         "warm prefix hits vanish (the injected "
                         "regression)")
    ap.add_argument("--no-prefetch", action="store_true",
                    help="disable the two-tier KV probe's cursor-ahead "
                         "staging: every parked-sequence restore "
                         "becomes a counted stall and prefetch hits "
                         "read 0 (the injected regression)")
    ap.add_argument("--colocated", action="store_true",
                    help="serve the disagg probe's scenarios with "
                         "roles=None: zero pages move over the fabric, "
                         "the fleet prefix cache never hits, and the "
                         "TTFT ratio collapses to ~1 (the injected "
                         "regression)")
    ap.add_argument("--per-layer", action="store_true",
                    help="force the megakernel probe's measured engine "
                         "back to layer scope: launches per token rise "
                         "from 1.0 to num_layers and the compiled "
                         "fusion/kernel counts rise (the injected "
                         "regression)")
    ap.add_argument("--per-layer-prefill", action="store_true",
                    help="build the fused-prefill measurement's engine "
                         "UNFUSED: the compiled ragged-step counts "
                         "climb back to the unfused floor and the "
                         "flood TTFT ratio reads 1.0 (the injected "
                         "regression)")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="replace the pipeline probe's staged runs "
                         "with pp=1 gradient accumulation at the same "
                         "microbatch count: ring permutes read 0, the "
                         "max-stage fraction reads 1.0, the bubble "
                         "reads 0 (the injected regression)")
    ap.add_argument("--no-fairness", action="store_true",
                    help="serve the multitenant probe's noisy-neighbor "
                         "flood with no tenant policy (bare FIFO): "
                         "quota sheds read 0 and the good tenant's p99 "
                         "TTFT blows out (the injected regression)")
    args = ap.parse_args(argv)

    probes = tuple(p for p in args.probes.split(",") if p)
    unknown = set(probes) - set(PROBES)
    if unknown:
        print(f"unknown probes: {sorted(unknown)}", file=sys.stderr)
        return 2
    if args.record and args.compare is not None:
        # record-then-compare-against-itself would always pass; an
        # operator asking for both almost certainly wants a real gate
        # first — make them choose
        print("--record and --compare are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.record and set(probes) != set(PROBES):
        # a partial recording would overwrite the backend's baseline
        # with a subset and every later full compare would read the
        # dropped metrics as "(new, no baseline)" — silent coverage loss
        print("--record requires the full probe set (a partial "
              "recording would shrink gate coverage)", file=sys.stderr)
        return 2
    current = collect(probes=probes, burst_tokens=args.burst_tokens,
                      spec_tokens=args.spec_tokens,
                      gspmd_dp_only=args.dp_only,
                      cluster_retry_budget=0 if args.no_retry else 2,
                      fusion_defuse=args.defuse,
                      telemetry_burn_alerts=not args.no_burn_alerts,
                      persist_corrupt=args.corrupt_checkpoint,
                      kvtier_prefetch=not args.no_prefetch,
                      disagg_colocated=args.colocated,
                      multitenant_fairness=not args.no_fairness,
                      megakernel_per_layer=args.per_layer,
                      pipeline_no_pp=args.no_pipeline,
                      megakernel_per_layer_prefill=args.per_layer_prefill)

    if args.json:
        # --json changes the output format, never the action: combined
        # with --compare (or --record) the gate/recording still runs
        # and still sets the exit code
        print(json.dumps(current, indent=1, sort_keys=True))
        if args.compare is None and not args.record:
            return 0
    elif not args.record and args.compare is None:
        print(json.dumps(current, indent=1, sort_keys=True))
        return 0

    if args.record:
        # a baseline with a null metric (or a probe that errored) would
        # make gate() read that metric as "(new, no baseline)" forever —
        # coverage silently lost on the RECORDING side of the compare
        nulls = sorted(k for k, v in current["metrics"].items()
                       if v is None)
        if nulls or current.get("probe_errors"):
            print(f"refusing to record a broken collection: null "
                  f"metrics {nulls}, probe errors "
                  f"{current.get('probe_errors')}", file=sys.stderr)
            return 2
        baselines = {}
        if os.path.exists(BASELINE_PATH):
            with open(BASELINE_PATH) as f:
                baselines = json.load(f)
        baselines[current["backend"]] = current
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
            f.write("\n")
        # status goes to stderr under --json: stdout stays pure JSON
        print(f"recorded baseline for backend={current['backend']} "
              f"({len(current['metrics'])} metrics) -> {BASELINE_PATH}",
              file=sys.stderr if args.json else sys.stdout)
        return 0

    try:
        with open(args.compare) as f:
            baselines = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read baseline {args.compare}: {e}", file=sys.stderr)
        return 2
    baseline = baselines.get(current["backend"])
    if baseline is None:
        print(f"no baseline for backend={current['backend']} in "
              f"{args.compare}; run `python -m tools.proxy_bench "
              f"--record` first", file=sys.stderr)
        return 2
    failures, report = gate(current, baseline,
                            require_all=set(probes) == set(PROBES))
    # with --json, stdout is the collection JSON and nothing else (it
    # must stay machine-parseable); the human report moves to stderr
    dst = sys.stderr if args.json else sys.stdout
    print(f"proxy bench gate  backend={current['backend']} "
          f"probes={','.join(sorted(probes))}", file=dst)
    print(report, file=dst)
    if current.get("probe_errors"):
        print(f"probe errors: {current['probe_errors']}", file=sys.stderr)
    if failures:
        print("FAIL: " + "; ".join(f"{n}: {r}" for n, r in failures),
              file=sys.stderr)
        return 1
    print("PASS", file=dst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
