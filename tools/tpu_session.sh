#!/usr/bin/env bash
# Chip-session queue (round-4 continuation). Run when the pool answers;
# ONE TPU process at a time, each step exits cleanly (no SIGKILL of
# claim holders — that wedges the pool for 10+ minutes or hours).
#
#   bash tools/tpu_session.sh [bench|sweep|audit|opbench|all]
#
# Order matters: bench first (the artifact that counts), then the
# attention-geometry sweep that decides the next 1B config, then the
# audit + op baseline.
set -euo pipefail
cd "$(dirname "$0")/.."

what="${1:-all}"

probe() {
  echo "== probing the chip (100s) =="
  timeout 100 python -c "import jax; print(jax.devices())" || {
    echo "chip unreachable; aborting (leave the pool QUIET >=15 min)" >&2
    exit 2
  }
}

case "$what" in
  bench|all)
    probe
    echo "== bench: llama_125m + llama_1b (post-GQA-native) =="
    timeout 2400 python bench.py
    echo "==> update tools/bench_lastgood.json with the fresh numbers"
    ;;&
  sweep|all)
    probe
    echo "== attention geometry sweep: h32/d64 vs h16/d128 vs splash =="
    # /tmp/exp4_attn.py from the session, or regenerate: it measures
    # fwd+bwd marginal-slope at the exact 1B shapes
    PYTHONPATH=. timeout 560 python tools/attn_sweep_1b.py
    echo "==> if h16/d128 wins materially, flip bench.py llama_1b to"
    echo "    num_attention_heads=16 and re-run bench"
    ;;&
  audit|all)
    probe
    echo "== perf audit: matmul / attention (incl. 1B rows) / step =="
    timeout 900 python tools/perf_audit.py matmul
    timeout 900 python tools/perf_audit.py attention
    timeout 1200 python tools/perf_audit.py step
    echo "==> reconcile docs/PERF.md tables with docs/PERF_AUDIT.json"
    ;;&
  opbench|all)
    probe
    echo "== op bench: record the TPU baseline =="
    timeout 900 python tools/op_bench.py --record --no-collective
    ;;&
  bench|sweep|audit|opbench|all)
    : ;;
  *)
    echo "usage: $0 [bench|sweep|audit|opbench|all]" >&2
    exit 1
    ;;
esac
echo done