"""Generate FLAGS_DISPOSITION.md: every reference flag mapped to a
disposition (round-5 verdict item 8 — close the flags book, no
"remaining" bucket).

Dispositions:
  implemented   — registered in paddle_tpu.core.flags with wired behavior
  n/a-cuda      — CUDA/cuDNN/cuBLAS/TensorRT/ROCm/XPU/OneDNN specifics
                  with no TPU analog (XLA owns the role)
  n/a-ps        — parameter-server / GPU-graph / slot-record training
                  stack (sanctioned descope, SURVEY section 2.4)
  n/a-compiler  — PIR/CINN/prim/dy2st compiler internals collapsed into
                  jaxpr/StableHLO + XLA by design
  n/a-legacy    — old executor / scope GC / misc legacy runtime

Usage: python tools/gen_flags_disposition.py [--check]
  --check exits nonzero if any reference flag lacks a disposition or an
  "implemented" flag is not actually registered.
"""
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_FLAGS_CC = "/root/reference/paddle/common/flags.cc"

# Non-"implemented" dispositions, each with a one-line reason.
NA = {}


def _na(kind, reason, *names):
    for n in names:
        NA[n] = (kind, reason)


_na("n/a-cuda", "CUDA library path discovery (dlopen search dirs)",
    "cublas_dir", "cudnn_dir", "cupti_dir", "curand_dir", "cusolver_dir",
    "cusparse_dir", "cusparselt_dir", "nccl_dir", "nvidia_package_dir",
    "mkl_dir", "mklml_dir", "lapack_dir", "op_dir", "win_cuda_bin_dir")
_na("n/a-cuda", "cuBLAS/cuBLASLt gemm tuning — the MXU path is XLA-owned",
    "enable_cublas_tensor_op_math", "cublaslt_exhaustive_search_times",
    "cublaslt_device_best_config", "enable_blaslt_global_search",
    "cuda_core_int8_gemm")
_na("n/a-cuda", "cuDNN/MIOpen kernel selection — conv lowers to XLA",
    "conv2d_disable_cudnn", "enable_cudnn_frontend",
    "cudnn_cache_saturation_count", "batch_norm_use_miopen",
    "manually_trans_conv_filter")
_na("n/a-cuda", "CUDA allocator strategy (pinned/async/vmm pools); device "
    "memory is PJRT-owned on TPU",
    "fraction_of_cuda_pinned_memory_to_use",
    "use_auto_growth_pinned_allocator", "use_cuda_malloc_async_allocator",
    "cuda_malloc_async_pool_memory_throttle_ratio",
    "pinned_memory_as_cpu_backend", "sync_after_alloc",
    "initial_gpu_memory_in_mb", "reallocate_gpu_memory_in_mb",
    "auto_free_cudagraph_allocations_on_launch")
_na("n/a-cuda", "CUDA-graph / stream capture executor modes",
    "new_executor_use_cuda_graph",
    "pir_interpreter_record_stream_for_gc_cache",
    "allreduce_record_one_event")
_na("n/a-cuda", "GPU serving-kernel variants (XQA/mbFMHA/partitioning)",
    "use_xqa_optim", "fused_multi_transformer_op_use_mbfmha",
    "multi_block_attention_min_partition_size")
_na("n/a-cuda", "TensorRT integration",
    "trt_ibuilder_cache", "trt_min_group_size")
_na("n/a-cuda", "XPU/NPU kernel-primitive toggles",
    "run_kp_kernel", "npu_storage_format")
_na("n/a-cuda", "OneDNN tracer op lists — no OneDNN tier on this stack",
    "use_mkldnn", "tracer_onednn_ops_on", "tracer_onednn_ops_off")
_na("n/a-ps", "parameter-server communicator knobs (sanctioned descope)",
    "communicator_is_sgd_optimizer", "communicator_max_merge_var_num",
    "communicator_send_queue_size", "enable_sparse_inner_gather",
    "query_dest_rank_by_multi_node", "enable_auto_rdma_trans",
    "enable_all2all_use_fp16", "enable_tracker_all2all")
_na("n/a-ps", "GPU-graph / graph-sampling training stack",
    "enable_graph_multi_node_sampling", "enable_neighbor_list_use_uva",
    "graph_embedding_split_infer_mode", "graph_get_neighbor_id",
    "graph_load_in_parallel", "graph_metapath_split_opt",
    "graph_neighbor_size_percent", "multi_node_sample_use_gpu_table",
    *[f for f in ("gpugraph_debug_gpu_memory",
                  "gpugraph_dedup_pull_push_mode",
                  "gpugraph_enable_gpu_direct_access",
                  "gpugraph_enable_hbm_table_collision_stat",
                  "gpugraph_enable_segment_merge_grads",
                  "gpugraph_hbm_table_load_factor",
                  "gpugraph_load_node_list_into_hbm",
                  "gpugraph_merge_grads_segment_size",
                  "gpugraph_slot_feasign_max_num",
                  "gpugraph_sparse_table_storage_mode",
                  "gpugraph_storage_mode")])
_na("n/a-ps", "slot-record / ins-parser feed pipeline",
    "enable_slotpool_wait_release", "enable_slotrecord_reset_shrink",
    "enable_ins_parser_file", "enable_opt_get_features",
    "record_pool_max_size", "slotpool_thread_num")
_na("n/a-compiler", "PIR pass pipeline — jaxpr/StableHLO is the IR here",
    "pir_apply_inplace_pass", "pir_apply_shape_optimization_pass",
    "pir_broadcast_tree_limit", "enable_pir_in_executor_trace_run",
    "enable_pir_with_pt_in_dy2st", "check_infer_symbolic",
    "ir_inplace_kernel_blacklist", "enable_auto_layout_pass",
    "enable_fuse_parallel_matmul_pass", "enable_adjust_op_order",
    "logging_pir_py_code_dump_symbolic_dims",
    "disable_logging_op_attr_list", "enable_custom_engine")
_na("n/a-compiler", "CINN fusion tuning — XLA owns fusion on TPU",
    "cinn_compile_thread_num", "cinn_input_dynamic_dim_spec_file",
    "cinn_specify_input_dynamic_dim", "enable_fusion_result_check",
    "enable_append_iters_in_fusion", "enable_reuse_iters_in_fusion",
    "enable_transpose_iters_in_fusion", "cse_max_count",
    "enable_cse_in_dy2st")
_na("n/a-compiler", "prim (operator decomposition) — JAX AD provides it",
    "prim_enable_dynamic", "prim_forward_blacklist", "prim_skip_dynamic")
_na("n/a-legacy", "legacy executor scope GC / sub-scope pooling",
    "eager_delete_scope", "fast_eager_deletion_mode",
    "local_exe_sub_scope_limit")
_na("n/a-legacy", "dy2st static-runtime data dump (old SOT debugging)",
    "save_cf_stack_op", "save_static_runtime_data",
    "static_runtime_data_save_path")


def ref_flag_names():
    src = open(REF_FLAGS_CC).read()
    return sorted(set(re.findall(
        r"(?:PHI|PD)_DEFINE_(?:EXPORTED_)?"
        r"(?:bool|int32|int64|uint64|double|string)\(\s*([a-z0-9_]+)",
        src)))


def registered_names():
    sys.path.insert(0, REPO)
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu  # noqa: F401 — registers all flags
    from paddle_tpu.core.flags import GLOBAL_FLAGS
    return set(GLOBAL_FLAGS._flags)


def main():
    ref = ref_flag_names()
    ours = registered_names()
    rows = []
    missing = []
    for name in ref:
        if name in ours:
            rows.append((name, "implemented",
                         "registered + behavior-tested "
                         "(tests/test_flags_behavior.py)"))
        elif name in NA:
            kind, reason = NA[name]
            rows.append((name, kind, reason))
        else:
            missing.append(name)
            rows.append((name, "UNDISPOSITIONED", "!!"))
    counts = {}
    for _, kind, _ in rows:
        counts[kind] = counts.get(kind, 0) + 1
    out = [
        "# Flags disposition — every reference flag accounted for",
        "",
        "Generated by `tools/gen_flags_disposition.py` from",
        "`/root/reference/paddle/common/flags.cc` and the live",
        "`paddle_tpu.core.flags` registry. Reference flags: "
        f"**{len(ref)}** — " + ", ".join(
            f"{k}: {v}" for k, v in sorted(counts.items())) + ".",
        "",
        "Extra flags registered here beyond the reference's common set "
        f"(TPU-native knobs, SOT cache bounds, Pallas thresholds): "
        f"{len(ours - set(ref))}.",
        "",
        "| reference flag | disposition | why |",
        "|---|---|---|",
    ]
    for name, kind, reason in rows:
        out.append(f"| `{name}` | {kind} | {reason} |")
    path = os.path.join(REPO, "FLAGS_DISPOSITION.md")
    with open(path, "w") as f:
        f.write("\n".join(out) + "\n")
    print(f"wrote {path}: {len(rows)} flags, {counts}")
    if missing:
        print("UNDISPOSITIONED:", missing)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
