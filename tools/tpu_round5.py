"""Round-5 chip-window orchestrator — runs the evidence chain in VERDICT
priority order the moment the pool answers, budgeting for a short window.

Order (VERDICT r4 items 1-2, budgeted so a ~20-minute window still lands
the headline):
  1. attn_sweep_1b  — d64-vs-d128 / block-size / splash decision data
  2. llama_1b bench with the sweep's winning geometry
  3. llama_125m bench
  4. llama_1b bench with the other geometry (A/B completeness)
  5. perf_audit attention / matmul / step
  6. op_bench --record (TPU per-op baseline)

Every completed stage appends to tools/round5_evidence.log and good bench
payloads are recorded into tools/bench_lastgood.json with dated history
(VERDICT r4 weak #8: append-dated records; keep best AND latest).

ONE TPU process at a time: each stage is a subprocess that exits before
the next starts.
"""
import json
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
LOG = os.path.join(HERE, "round5_evidence.log")
T0 = time.time()


def log(msg):
    line = f"[{time.strftime('%H:%M:%S', time.gmtime())}] [+{time.time()-T0:6.0f}s] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def run(cmd, timeout, env=None):
    log(f"RUN ({timeout:.0f}s budget): {' '.join(cmd)}")
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=full_env, cwd=REPO)
        out = (proc.stdout or "") + ("\n--stderr--\n" + proc.stderr
                                     if proc.returncode else "")
        for line in out.strip().splitlines():
            log(f"  | {line}")
        return proc.returncode, proc.stdout or ""
    except subprocess.TimeoutExpired as e:
        tail = (e.stdout or b"")
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        for line in tail.strip().splitlines()[-20:]:
            log(f"  | {line}")
        log(f"  TIMEOUT after {timeout:.0f}s")
        return -1, tail


def record_lastgood(config, payload):
    """Append a dated record; keep full history plus best-and-latest."""
    path = os.path.join(HERE, "bench_lastgood.json")
    try:
        with open(path) as f:
            blob = json.load(f)
    except (OSError, ValueError):
        blob = {}
    history = blob.get("history", [])
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    history.append({"recorded": stamp, "config": config, "parsed": payload})
    # latest full-run payload becomes the headline 'parsed' blob the bench
    # fallback reads; history preserves every prior number
    if config == "llama_125m":
        blob["parsed"] = payload
        blob["recorded"] = f"{stamp} (round-5 chip window)"
    blob["history"] = history
    with open(path, "w") as f:
        json.dump(blob, f, indent=1)
    log(f"recorded {config} -> bench_lastgood.json (history n={len(history)})")


SENTINEL = "BENCH_RESULT_JSON:"


def bench_child(config, heads=None, budget=900, attn_impl=None):
    env = {"PADDLE_TPU_BENCH_PROGRESS": f"/tmp/r5_prog_{time.time_ns()}"}
    if heads:
        env["PADDLE_TPU_BENCH_1B_HEADS"] = str(heads)
    if attn_impl:
        env["PADDLE_TPU_ATTN_IMPL"] = attn_impl
    rc, out = run([sys.executable, os.path.join(REPO, "bench.py"), "--child",
                   f"--config={config}"], budget, env)
    for line in out.splitlines():
        if line.startswith(SENTINEL):
            payload = json.loads(line[len(SENTINEL):])
            if "error" not in payload:
                if heads:
                    payload["heads"] = heads
                if attn_impl:
                    payload["attn_impl"] = attn_impl
                record_lastgood(config, payload)
                return payload
    return None


def main():
    log("=== round-5 evidence chain start ===")
    # Stage 1: the attention-geometry sweep (the round's defining data)
    rc, sweep_out = run([sys.executable,
                         os.path.join(HERE, "attn_sweep_1b.py")], 600)
    # Parse winner: compare best d64 time vs best d128 time across impls
    best = {64: float("inf"), 128: float("inf")}
    impl = {64: "?", 128: "?"}
    for line in sweep_out.splitlines():
        m = re.match(r"h(\d+) d(\d+) (\S.*?):\s+([\d.]+) ms", line)
        if m:
            d = int(m.group(2))
            t = float(m.group(4))
            if d in best and t < best[d]:
                best[d] = t
                impl[d] = m.group(3)
    if best[128] < best[64]:
        win_heads, lose_heads = 16, 32
    else:
        win_heads, lose_heads = 32, 16
    log(f"sweep verdict: d64 best {best[64]:.2f} ms ({impl[64]}), "
        f"d128 best {best[128]:.2f} ms ({impl[128]}) -> heads={win_heads}")

    def record_geometry(heads, attn_impl=None, basis=""):
        """Adopt a MEASURED winner as the bench default (env overrides)."""
        path = os.path.join(HERE, "attn_geometry.json")
        blob = {"heads": heads,
                "recorded": time.strftime("%Y-%m-%d %H:%M UTC",
                                          time.gmtime()),
                "basis": basis}
        if attn_impl:
            blob["attn_impl"] = attn_impl
        with open(path, "w") as f:
            json.dump(blob, f, indent=1)
        log(f"adopted geometry: {blob}")

    win_key = 128 if win_heads == 16 else 64
    if best[win_key] != float("inf"):
        record_geometry(win_heads,
                        basis=f"attn_sweep_1b: d64 {best[64]:.2f} ms vs "
                              f"d128 {best[128]:.2f} ms")

    # Stage 2: 1B bench, winning geometry — the headline number
    p_auto = bench_child("llama_1b", heads=win_heads, budget=1100)
    if p_auto:
        log(f"HEADLINE llama_1b heads={win_heads}: MFU {p_auto.get('mfu')} "
            f"tok/s {p_auto.get('value')}")

    # Stage 3: 125m bench (the lastgood headline config)
    p = bench_child("llama_125m", budget=700)
    if p:
        log(f"llama_125m: MFU {p.get('mfu')} tok/s {p.get('value')}")

    # Stage 4: 1B winner geometry with the splash production kernel —
    # the step-level attention A/B the microbench can't settle
    p = bench_child("llama_1b", heads=win_heads, budget=1100,
                    attn_impl="splash")
    if p:
        log(f"llama_1b heads={win_heads} splash: MFU {p.get('mfu')} "
            f"tok/s {p.get('value')}")
        if p_auto and p.get("mfu", 0) > p_auto.get("mfu", 0) * 1.02:
            # splash beats the auto tier by >2% at the STEP level:
            # adopt it for the bench default too
            record_geometry(win_heads, attn_impl="splash",
                            basis=f"step A/B: splash MFU {p['mfu']} vs "
                                  f"auto {p_auto['mfu']}")

    # Stage 4b: 1B other geometry (A/B completeness)
    p = bench_child("llama_1b", heads=lose_heads, budget=1100)
    if p:
        log(f"llama_1b heads={lose_heads}: MFU {p.get('mfu')} "
            f"tok/s {p.get('value')}")

    # Stage 5: perf audit (attention first — it feeds PERF.md 2a)
    for what, budget in (("attention", 900), ("matmul", 900), ("step", 1200)):
        run([sys.executable, os.path.join(HERE, "perf_audit.py"), what],
            budget)

    # Stage 6: TPU op-bench baseline
    run([sys.executable, os.path.join(HERE, "op_bench.py"), "--record",
         "--no-collective"], 900)
    log("=== evidence chain complete ===")


if __name__ == "__main__":
    main()
