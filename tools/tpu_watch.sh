#!/usr/bin/env bash
# Chip-liveness watcher: probe on a 25-minute cadence (the claim
# discipline's safe spacing — see .claude/skills/verify); when the pool
# answers, run the round-4 evidence chain once and exit.
#
#   bash tools/tpu_watch.sh [logfile]
#
# Produces (on success): regenerated docs/PERF_AUDIT.json sections, a
# fresh bench line in the log, the d64-vs-d128 1B A/B, and the TPU
# op-bench baseline. ONE TPU process at a time throughout.
set -uo pipefail
cd "$(dirname "$0")/.."

log="${1:-/tmp/tpu_watch.log}"
echo "[watch] start $(date -u +%H:%M:%S)" >> "$log"

while true; do
  if timeout 120 python -c "import jax; print(jax.devices())" \
      >> "$log" 2>&1; then
    echo "[watch] chip ALIVE $(date -u +%H:%M:%S) — running evidence" \
      >> "$log"
    {
      echo "== audit matmul =="
      timeout 900 python tools/perf_audit.py matmul
      echo "== audit attention =="
      timeout 900 python tools/perf_audit.py attention
      echo "== audit step =="
      timeout 1200 python tools/perf_audit.py step
      echo "== bench (both configs) =="
      timeout 2400 python bench.py
      echo "== 1B d128 A/B =="
      PADDLE_TPU_BENCH_1B_HEADS=16 timeout 1500 python bench.py --child \
        --config=llama_1b
      echo "== opbench TPU baseline =="
      timeout 900 python tools/op_bench.py --record --no-collective
      echo "[watch] evidence chain complete $(date -u +%H:%M:%S)"
    } >> "$log" 2>&1
    exit 0
  fi
  echo "[watch] wedged $(date -u +%H:%M:%S); sleeping 25m" >> "$log"
  sleep 1500
done
