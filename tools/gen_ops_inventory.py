"""Regenerate OPS_INVENTORY.md: every reference forward-op schema vs the
live paddle_tpu surface (run from the repo root; needs /root/reference).

    python tools/gen_ops_inventory.py

"yes" rows are verified against the imported package, not hand-claimed;
the mapping table below documents where renamed/collapsed/descoped
capabilities live.
"""
import re

import paddle_tpu as paddle
from paddle_tpu.core.dispatch import OPS
import paddle_tpu.tensor as T
import paddle_tpu.nn.functional as F

REF_YAMLS = (
    "/root/reference/paddle/phi/ops/yaml/ops.yaml",
    "/root/reference/paddle/phi/ops/yaml/inconsistent/dygraph_ops.yaml",
)

OPT = ("optimizer class applies the update (paddle_tpu/optimizer, pure "
       "jitted update fns)")
AMP = "amp/grad_scaler.py performs the same check/update inside the scaler"
COLL = "distributed/collective.py (eager multi-process + shard_map regimes)"
QUANT = ("quantization/ observers + quantize_to_int8/fake_quantize cover "
         "the capability")
LEGACY = ("legacy LoD/sequence stack; SURVEY sanctions descope (no "
          "LoDTensor in this design)")
PS = "parameter-server / distributed-CPU training stack; sanctioned descope"
DETZOO = ("detection-model zoo CUDA kernel; vision.ops covers the core "
          "(nms/roi_align/box_iou), remainder descoped until a detection "
          "zoo lands")
GRAPHNN = "graph-learning sampler stack (GraphSAGE et al.); descoped domain"
XPUDEV = "vendor-device-specific op; no analog needed on the XLA stack"
MOE = ("incubate/distributed/models/moe + distributed/expert_parallel.py "
       "implement gating/dispatch/combine as one fused path")

M = {}


def put(names, status, note):
    for n in names.split():
        M[n] = (status, note)


put("adadelta_ adagrad_ adam_ adamax_ adamw_ asgd_ lamb_ momentum_ nadam_ "
    "radam_ rmsprop_ rprop_ sgd_ ftrl dpsgd decayed_adagrad fused_adam_ "
    "merged_adam_ merged_momentum_ average_accumulates_", "collapsed", OPT)
put("check_finite_and_unscale_ update_loss_scaling_", "collapsed", AMP)
put("all_gather all_reduce all_to_all barrier broadcast reduce "
    "reduce_scatter c_allreduce_sum c_concat c_identity c_scatter c_split "
    "mp_allreduce_sum partial_allgather partial_concat partial_sum "
    "sync_calc_stream sync_comm_stream", "collapsed", COLL)
put("c_embedding", "as",
    "fleet VocabParallelEmbedding (distributed/fleet/mp_layers.py)")
put("global_gather global_scatter moe_dispatch moe_ffn moe_reduce "
    "number_count limit_by_capacity prune_gate_by_capacity random_routing "
    "assign_pos", "collapsed", MOE)
put("fake_channel_wise_dequantize_max_abs fake_channel_wise_quantize_abs_max "
    "fake_channel_wise_quantize_dequantize_abs_max fake_dequantize_max_abs "
    "fake_quantize_abs_max fake_quantize_dequantize_abs_max "
    "fake_quantize_dequantize_moving_average_abs_max "
    "fake_quantize_moving_average_abs_max fake_quantize_range_abs_max "
    "dequantize_abs_max dequantize_log apply_per_channel_scale "
    "lookup_table_dequant", "collapsed", QUANT)
put("llm_int8_linear", "as",
    "incubate.nn.functional.weight_only_linear / llm_int8_linear")
put("sequence_conv sequence_pool im2sequence attention_lstm "
    "match_matrix_tensor chunk_eval crf_decoding ctc_align cvm batch_fc "
    "rank_attention shuffle_batch pyramid_hash tdm_child tdm_sampler "
    "add_position_encoding", "descoped", LEGACY)
put("dgc dgc_clip_by_norm dgc_momentum", "descoped", PS)
DET = ("paddle_tpu.vision.ops / vision/detection.py — static-shape jnp "
       "decoders + masked-NMS family with host compaction, numpy-oracle "
       "tests (tests/test_detection_ops.py); SSDLite proves composition")
put("box_clip box_coder distribute_fpn_proposals generate_proposals "
    "matrix_nms multiclass_nms3 prior_box psroi_pool roi_pool yolo_box",
    "as", DET)
put("deformable_conv", "as",
    "vision.ops.deform_conv2d (bilinear-gather im2col, v1/v2 mask, "
    "differentiable)")
put("bipartite_match", "as",
    "vision.ops.bipartite_match (kernel-greedy + per_prediction argmax)")
put("temporal_shift", "as",
    "nn.functional.temporal_shift (TSM pad-and-slice, doc-exact)")
put("collect_fpn_proposals", "as",
    "vision.ops.collect_fpn_proposals (global top-k + per-image re-sort)")
put("affine_channel", "as", "vision.ops.affine_channel")
put("yolo_loss", "as",
    "vision.ops.yolo_loss (vectorized kernel-exact loss: SCE/L1 terms, "
    "anchor assignment, ignore mask, label smooth; oracle-tested)")
put("correlation", "as",
    "vision.ops.correlation (FlowNet displacement correlation, "
    "loop-oracle tested)")
put("yolo_box_head yolo_box_post", "collapsed",
    "TensorRT-fusion inference ops; yolo_box + multiclass_nms3 compose "
    "the same path on this stack")
GEO = ("paddle_tpu.geometric — gather + jax.ops.segment_* message passing, "
       "reindex, CSC neighbor sampling (tests/test_geometric.py)")
put("graph_sample_neighbors reindex_graph send_u_recv "
    "send_ue_recv send_uv weighted_sample_neighbors", "as", GEO)
put("graph_khop_sampler", "as",
    "geometric.graph_khop_sampler (multi-hop frontier sampling + "
    "first-appearance reindex)")
put("npu_identity", "descoped", XPUDEV)
put("nms roi_align", "as",
    "paddle_tpu.vision.ops (nms, roi_align w/ sampling_ratio)")
put("accuracy auc", "as", "paddle_tpu.metric (Accuracy/Auc)")
put("accuracy_check check_numerics", "as",
    "FLAGS_check_nan_inf sanitizer (eager sweep + compiled fused check)")
put("enable_check_model_nan_inf disable_check_model_nan_inf", "as",
    "paddle.set_flags({'FLAGS_check_nan_inf': ...})")
put("as_strided index_select_strided tensor_unfold view_dtype view_shape "
    "view_slice trans_layout", "collapsed",
    "jax arrays are logical values: strided views collapse into "
    "gather/reshape/bitcast (Tensor.reshape, paddle.unfold, "
    "lax.bitcast_convert_type); no stride metadata exists")
put("assign_out_ assign_value_ set set_value set_value_with_tensor "
    "share_data copy_to memcpy_d2h memcpy_h2d", "collapsed",
    "functional value semantics: Tensor.__setitem__/paddle.assign/device "
    "placement (core/tensor.py, device/)")
put("data full_int_array full_with_tensor full_batch_size_like "
    "uniform_random_batch_size_like", "collapsed",
    "static-graph feed/attr materialization ops; dygraph+jit traces python "
    "literals directly")
put("depend", "collapsed",
    "executor-ordering token; XLA dataflow ordering makes it meaningless")
put("is_empty mean_all l1_norm elementwise_pow", "as",
    "tensor/math.py (numel==0 via Tensor.size, mean, norm family, pow)")
put("fill fill_diagonal fill_diagonal_tensor", "as",
    "tensor/math.py fill_/fill_diagonal_/fill_diagonal_tensor")
put("gaussian_inplace uniform_inplace truncated_gaussian_random "
    "standard_gamma dirichlet", "as",
    "tensor/random.py + nn.initializer (Normal/Uniform/TruncatedNormal) + "
    "distribution (Dirichlet/Gamma sampling)")
put("bce_loss kldiv_loss log_loss hinge_loss identity_loss "
    "sigmoid_cross_entropy_with_logits cross_entropy_with_softmax", "as",
    "nn/functional/loss.py (binary_cross_entropy[_with_logits], kl_div, "
    "softmax_with_cross_entropy; log/hinge via square_error_cost family)")
put("warpctc warprnnt", "as",
    "nn/functional/loss.py ctc_loss + rnnt_loss (lax.scan forward "
    "algorithms with FastEmit; numpy-DP-oracle tests)")
put("flash_attn flash_attn_qkvpacked "
    "flash_attn_varlen_qkvpacked flashmask_attention "
    "memory_efficient_attention sparse_attention calc_reduced_attn_scores",
    "as",
    "F.flash_attention / F.scaled_dot_product_attention / "
    "F.flash_attn_unpadded (varlen segments) + kernels/flash_attention.py "
    "(Pallas) + kernels/paged_attention.py; qkvpacked layouts unpack "
    "trivially")
put("masked_multihead_attention_", "as",
    "models/generation.py decode step + kernels/paged_attention.py")
put("fused_batch_norm_act fused_bn_add_activation fused_gemm_epilogue "
    "fused_softmax_mask fused_softmax_mask_upper_triangle "
    "conv2d_transpose_bias", "collapsed",
    "XLA fuses these compositions (SURVEY C12 analysis); "
    "incubate.nn.functional keeps explicit fused_* entry points")
put("bicubic_interp bilinear_interp linear_interp nearest_interp "
    "trilinear_interp", "as", "F.interpolate(mode=...)")
put("pool2d pool3d max_pool2d_with_index max_pool3d_with_index "
    "fractional_max_pool2d fractional_max_pool3d unpool unpool3d", "as",
    "nn/functional/pooling.py (avg/max/adaptive + return_mask in 1/2/3-D; "
    "max_unpool1d/2d/3d scatter inverses; fractional_max_pool2d/3d with "
    "the kernel's exact index sequences)")
put("depthwise_conv2d depthwise_conv2d_transpose", "as",
    "F.conv2d(groups=in_channels) - XLA lowers grouped conv to the "
    "depthwise path")
put("gru gru_unit lstm rnn cudnn_lstm beam_search gather_tree", "as",
    "nn/layer/rnn.py (LSTM/GRU/SimpleRNN over lax.scan) + F.gather_tree; "
    "beam search orchestration in models/generation.py")
put("edit_distance", "as", "paddle_tpu.text.edit_distance")
put("frame overlap_add stft", "as",
    "paddle_tpu.signal (frame/overlap_add/stft/istft)")
put("logsigmoid tanh_shrink", "as", "F.log_sigmoid / F.tanhshrink")
put("reverse", "as", "paddle.flip")
put("repeat_interleave_with_tensor_index", "as",
    "paddle.repeat_interleave(tensor repeats)")
put("split_with_num", "as", "paddle.split(num_or_sections=int)")
put("lu_unpack matrix_rank_atol_rtol matrix_rank_tol", "as",
    "tensor/linalg.py lu/matrix_rank (tolerance variants partial)")
put("merge_selected_rows embedding_grad_dense "
    "embedding_with_scaled_gradient", "collapsed",
    "no SelectedRows type: embedding grads are dense scatter-adds by "
    "design (core/autograd accumulation)")
put("shape shape64", "collapsed",
    "Tensor.shape property (static shapes under XLA)")
put("shuffle_channel", "as", "F.channel_shuffle")
put("sync_batch_norm_", "as",
    "nn SyncBatchNorm collapses to BatchNorm under GSPMD (batch stats are "
    "global in the single-program model)")
put("top_p_sampling", "as",
    "models/generation.py _sample (top-p nucleus filter)")
put("read_file decode_jpeg", "as",
    "vision.ops.read_file/decode_jpeg (host PIL decode -> CHW uint8)")
put("coalesce_tensor", "collapsed",
    "fused-buffer packing for NCCL; XLA buffer assignment owns memory "
    "layout")
put("clip_by_norm", "as", "nn.ClipGradByNorm / paddle.clip + renorm")
put("segment_pool", "as",
    "incubate.nn.functional.segment_{sum,mean,max,min}")
put("pad3d", "as", "F.pad (NDHWC/NCDHW via data_format)")
put("viterbi_decode", "as",
    "paddle_tpu.text.viterbi_decode / ViterbiDecoder")
put("weight_dequantize weight_only_linear weight_quantize", "as",
    "incubate.nn.functional weight_quantize/weight_only_linear; int8 + "
    "nibble-packed int4 tiers (quantization.Int4Linear)")
put("add_n", "as", "paddle.add_n / chained paddle.add")


def main():
    ops = set()
    for f in REF_YAMLS:
        for line in open(f):
            m = re.match(r"- op\s*:\s*([a-z0-9_]+)", line)
            if m:
                ops.add(m.group(1))
    ref = sorted(ops)

    have = set(OPS)
    for mod in (paddle, T, F):
        have |= {n for n in dir(mod) if not n.startswith("_")}
    # surfaces beyond the three top-level namespaces
    import paddle_tpu.signal as signal_mod
    import paddle_tpu.text as text_mod
    import paddle_tpu.incubate.nn.functional as inc_f
    for mod in (signal_mod, text_mod, inc_f):
        have |= {n for n in dir(mod) if not n.startswith("_")}

    rows = []
    counts = {"yes": 0, "as": 0, "collapsed": 0, "descoped": 0, "todo": 0}
    for op in ref:
        if op in have or op.rstrip("_") in have:
            rows.append((op, "yes", "same name in the public surface (OPS "
                         "registry / paddle.* / F.* / signal / text / "
                         "incubate)"))
            counts["yes"] += 1
        elif op in M:
            s, note = M[op]
            rows.append((op, s, note))
            counts[s] += 1
        else:
            rows.append((op, "todo", "unmapped"))
            counts["todo"] += 1

    hdr = f"""# OPS_INVENTORY — reference forward-op schemas vs paddle_tpu

Audit artifact for SURVEY.md C8 ("no single op inventory to audit coverage
against"). Source of truth: every `- op:` entry in the reference's
`paddle/phi/ops/yaml/ops.yaml` + `inconsistent/dygraph_ops.yaml`
({len(ref)} forward ops). Regenerate: `python tools/gen_ops_inventory.py`
(the script introspects the live package, so "yes" rows are verified
imports, not claims).

Statuses:
- **yes** — same public name exists (eager OPS registry, `paddle.*`,
  `paddle.Tensor.*`, `paddle.nn.functional.*`, signal/text/incubate).
- **as** — implemented under the TPU-native name/module in the note.
- **collapsed** — the capability is subsumed by a design decision
  (functional value semantics, XLA fusion, GSPMD, optimizer classes...);
  the note says where the behavior lives.
- **descoped** — intentionally out of scope with the reason
  (legacy LoD stack, PS mode, vendor-device ops, domain zoos).
- **todo** — acknowledged gap.

Counts: {counts['yes']} yes / {counts['as']} as / \
{counts['collapsed']} collapsed / {counts['descoped']} descoped / \
{counts['todo']} todo.

| reference op | status | where / why |
|---|---|---|
"""
    body = "\n".join(f"| {op} | {s} | {note} |" for op, s, note in rows)
    sparse_section = _sparse_table()
    open("OPS_INVENTORY.md", "w").write(hdr + body + "\n" + sparse_section)
    print(counts)
    print("todos:", [op for op, s, _ in rows if s == "todo"])


# paddle.sparse ops with a deliberate non-sparse implementation; the note
# is the audit trail the round-3 verdict asked for (no silent holes)
SPARSE_NOTES = {
    "conv3d_implicit_gemm": ("as", "sparse.nn.functional conv3d path "
                             "(rulebook gather + MXU matmul — the implicit-"
                             "gemm formulation IS the TPU lowering)"),
    "sync_batch_norm_": ("as", "sparse.nn.BatchNorm over values + "
                         "distributed sync via GSPMD (dense stats are "
                         "tiny; a sparse-specific allreduce buys nothing)"),
    "batch_norm_": ("as", "sparse.nn.BatchNorm (normalizes stored values)"),
    "divide_scalar": ("as", "sparse.divide with a scalar operand"),
    "to_sparse_csr": ("as", "sparse_csr_tensor / SparseCsrTensor view"),
    "scale": ("as", "sparse values scale via sparse.multiply / dense "
              "scale on values"),
    "pca_lowrank": ("as", "sparse.pca_lowrank — densifies then SVDs: at "
                    "reference-supported sizes (q <= min(m,n)) one dense "
                    "XLA SVD on the MXU beats serialized sparse matvec "
                    "iterations; measured dense matmul numbers in "
                    "docs/PERF.md back the dense-wins call"),
    "mask_as": ("yes", ""),
    "masked_matmul": ("yes", "SDDMM at stored coordinates, O(nnz*k)"),
    "fused_attention": ("as", "sparse.nn.functional.attention (masked "
                        "softmax-attention over the stored pattern)"),
    "maxpool": ("as", "sparse.nn.functional.max_pool3d / nn.MaxPool3D"),
    "indices": ("as", "SparseCooTensor.indices() method"),
    "values": ("as", "SparseCooTensor.values() method"),
    "to_dense": ("as", "SparseCooTensor.to_dense() method"),
}


def _sparse_table():
    """Audit paddle.sparse against the reference sparse surface
    (sparse_ops.yaml + python/paddle/sparse exports) — round-3 verdict
    item 8: the table must have no silent holes."""
    import paddle_tpu.sparse as sp
    ref_ops = set()
    for line in open("/root/reference/paddle/phi/ops/yaml/sparse_ops.yaml"):
        m = re.match(r"- op\s*:\s*([a-z0-9_]+)", line)
        if m:
            ref_ops.add(m.group(1))
    # python-surface exports (binary/creation/multiary/unary __all__)
    for name in ("add", "divide", "is_same_shape", "mask_as",
                 "masked_matmul", "matmul", "multiply", "mv", "subtract",
                 "sparse_coo_tensor", "sparse_csr_tensor", "addmm",
                 "coalesce", "deg2rad", "rad2deg", "reshape", "slice",
                 "sum", "transpose", "pca_lowrank", "cast", "isnan",
                 "expm1", "log1p", "neg", "pow"):
        ref_ops.add(name)
    have = {n for n in dir(sp) if not n.startswith("_")}
    have |= {n for n in dir(sp.nn) if not n.startswith("_")}
    have |= {n for n in dir(sp.functional) if not n.startswith("_")}
    rows = []
    n_yes = n_as = n_todo = 0
    for op in sorted(ref_ops):
        if op in SPARSE_NOTES:
            s, note = SPARSE_NOTES[op]
            note = note or "same name in paddle_tpu.sparse"
            n_yes += s == "yes"
            n_as += s == "as"
        elif op in have or op.rstrip("_") in have:
            s, note = "yes", "same name in paddle_tpu.sparse"
            n_yes += 1
        else:
            s, note = "todo", "unmapped"
            n_todo += 1
        rows.append(f"| {op} | {s} | {note} |")
    body = "\n".join(rows)
    return f"""
## paddle.sparse surface (reference: sparse_ops.yaml + python/paddle/sparse)

{n_yes} yes / {n_as} as / {n_todo} todo of {len(ref_ops)} sparse ops.
Rows marked **as** document where a deliberately non-sparse (dense-XLA)
implementation wins on TPU and why.

| sparse op | status | where / why |
|---|---|---|
{body}
"""


if __name__ == "__main__":
    main()
