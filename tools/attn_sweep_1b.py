"""Attention at 1B train shapes: XLA vs our flash vs jax splash.

Marginal-slope timing (two fori_loop lengths, readback sync) per
tools/perf_audit.py — cancels the relay's fixed dispatch overhead.
Internal deadline; exits cleanly (never SIGKILL a claim holder).
"""
import math
import time

T0 = time.time()
DEADLINE = 480.0

import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_bench_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.kernels.flash_attention import flash_attention as pflash


def timed_device(fn, x, iters, repeats=3):
    looped = jax.jit(lambda y: jnp.sum(lax.fori_loop(
        0, iters, lambda i, y: fn(y), y).astype(jnp.float32)))
    float(looped(x))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(looped(x))
        best = min(best, time.perf_counter() - t0)
    return best / iters


def marginal(fn, x):
    t3 = timed_device(fn, x, 3) * 3
    t13 = timed_device(fn, x, 13) * 13
    return (t13 - t3) / 10


S = 2048
for H, D in ((32, 64), (16, 128)):
    if time.time() - T0 > DEADLINE:
        print("deadline hit, exiting clean", flush=True)
        break
    HKV = 4
    G = H // HKV
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, H, S, D)) * 0.1, jnp.bfloat16)
    kv = jnp.asarray(rng.standard_normal((1, HKV, S, D)) * 0.1, jnp.bfloat16)

    def gqa_sdpa(q, kv=kv, G=G, HKV=HKV, D=D):
        qg = q.reshape(1, HKV, G, S, D)
        logits = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kv) / math.sqrt(D)
        m = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(m, logits, -1e9).astype(jnp.float32)
        p = jax.nn.softmax(logits, -1).astype(q.dtype)
        return jnp.einsum("bhgqk,bhkd->bhgqd", p, kv).reshape(q.shape)

    def fb(fn):
        return jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32)))

    try:
        print(f"h{H} d{D} xla fwd+bwd: {marginal(fb(gqa_sdpa), q)*1e3:7.2f} ms",
              flush=True)
    except Exception as e:
        print(f"h{H} d{D} xla FAILED {type(e).__name__}: {e}"[:160], flush=True)
    for bq, bk in ((256, 512), (512, 512), (512, 1024)):
        if time.time() - T0 > DEADLINE:
            break
        try:
            t = marginal(fb(lambda q, bq=bq, bk=bk: pflash(
                q, kv, kv, causal=True, block_q=bq, block_k=bk)), q)
            print(f"h{H} d{D} ours bq{bq} bk{bk} fwd+bwd: {t*1e3:7.2f} ms",
                  flush=True)
        except Exception as e:
            print(f"h{H} d{D} ours bq{bq} FAILED {type(e).__name__}: {e}"[:160],
                  flush=True)
    # jax splash (production TPU kernel) — GQA-NATIVE via the MQA entry
    # (grouped K/V, no repeat), the same wrapper the step-level
    # PADDLE_TPU_ATTN_IMPL=splash path uses
    try:
        from paddle_tpu.kernels import splash_attention

        def run_splash(q, kv=kv):
            return splash_attention(q, kv, kv, causal=True)

        t = marginal(fb(run_splash), q)
        print(f"h{H} d{D} splash-gqa fwd+bwd: {t*1e3:7.2f} ms", flush=True)
    except Exception as e:
        print(f"h{H} d{D} splash FAILED {type(e).__name__}: {e}"[:200],
              flush=True)
print("DONE", flush=True)
