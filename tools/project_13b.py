"""Project Llama-2-13B full-pod MFU (v5p-128) from the roofline model,
anchored to the measured single-chip efficiency (round-5 verdict item 5).

Method: the auto-tuner's analytic roofline
(paddle_tpu/distributed/auto_tuner.py::estimate) prices compute, TP
all-reduces, the 1F1B pipeline bubble, and ZeRO reshard traffic. Its
"attainable compute" fraction is replaced by the MEASURED single-chip
anchor: the llama_1b train step's MFU (tools/bench_lastgood.json) under
three scenarios —

  measured : the recorded llama_1b point as-is (attention at d=64)
  d128     : attention geometry fixed (h16/d128 — projected from the
             measured attention share, docs/PERF.md section 2a)
  ceiling  : the measured pure-matmul fraction of nominal peak (the
             hardware practical ceiling, PERF.md section 1)

Pod MFU = global_flops / (t_step * n_chips * peak). Anything the anchor
already pays for (attention inefficiency, fusion overhead) is inherited;
the roofline adds only the DISTRIBUTED costs, so the projection is an
upper bound on what the same per-chip code reaches at pod scale.

Usage: python tools/project_13b.py [--markdown]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.distributed.auto_tuner import (   # noqa: E402
    CHIPS, candidates, estimate, memory_gb,
)

N_CHIPS = 128
CHIP = "v5p"
SEQ = 4096
GLOBAL_BATCH = 128          # 0.5M tokens/step at seq 4096

CFG_13B = {
    "hidden_size": 5120,
    "num_layers": 40,
    "num_attention_heads": 40,
    "vocab_size": 32000,
    "global_batch_size": GLOBAL_BATCH,
    # 13.0e9 params (Llama-2-13B card); 6*P*tokens train flops
    "n_params": 13.0e9,
}


def _measured_anchor():
    """Single-chip MFU from the last recorded llama_1b bench point."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_lastgood.json")
    try:
        with open(path) as f:
            blob = json.load(f)
        for rec in reversed(blob.get("history", [])):
            if rec.get("config") == "llama_1b" and \
                    rec.get("parsed", {}).get("mfu"):
                return float(rec["parsed"]["mfu"]), rec.get("recorded", "?")
        mfu = blob.get("parsed", {}).get("llama_1b", {}).get("mfu") \
            or blob.get("parsed", {}).get("mfu")
        if mfu:
            return float(mfu), blob.get("recorded", "?")
    except (OSError, ValueError):
        pass
    return 0.2028, "round-4 continuation (fallback constant)"


def project(anchor):
    """Best candidate and its projected pod MFU for a compute anchor."""
    peak = CHIPS[CHIP][0]
    best = None
    for cand in candidates(N_CHIPS, CFG_13B, max_mp=8, max_pp=8,
                           sharding_stages=(0, 1, 2),
                           micro_batch_sizes=(1, 2)):
        if memory_gb(cand, CFG_13B, seq_len=SEQ) > 90:   # v5p HBM 95G
            continue
        # estimate() prices compute at peak*0.5 (and the pipeline bubble
        # as a fraction of compute); re-price both at peak*anchor,
        # keeping the ICI communication terms as-is
        t = estimate(cand, CFG_13B, chip=CHIP, seq_len=SEQ)
        flops_per_dp = 6.0 * CFG_13B["n_params"] * \
            cand["micro_batch_size"] * cand["acc_steps"] * SEQ / \
            (cand["mp"] * cand["pp"])
        bubble = (cand["pp"] - 1) / \
            max(cand["acc_steps"] + cand["pp"] - 1, 1)
        t_compute_half = flops_per_dp / (peak * 0.5)
        t_comm = t - t_compute_half * (1 + bubble)
        t_anchored = t_comm + (flops_per_dp / (peak * anchor)) * (1 + bubble)
        global_flops = 6.0 * CFG_13B["n_params"] * GLOBAL_BATCH * SEQ
        mfu = global_flops / (t_anchored * N_CHIPS * peak)
        tok_s = GLOBAL_BATCH * SEQ / t_anchored
        if best is None or mfu > best[0]:
            best = (mfu, t_anchored, tok_s, cand)
    return best


def main():
    measured, src = _measured_anchor()
    scenarios = [
        ("measured (d64 attention)", measured),
        ("d128 attention geometry", 0.30),
        ("matmul practical ceiling", 0.40),
    ]
    rows = []
    for name, anchor in scenarios:
        mfu, t, tok_s, cand = project(anchor)
        rows.append((name, anchor, cand, t, tok_s, mfu))
    md = "--markdown" in sys.argv
    if md:
        print("| anchor scenario | 1-chip MFU | best layout | step (s) "
              "| tokens/s (pod) | projected pod MFU |")
        print("|---|---|---|---|---|---|")
    for name, anchor, cand, t, tok_s, mfu in rows:
        layout = (f"dp{cand['dp']} mp{cand['mp']} pp{cand['pp']} "
                  f"zero{cand['sharding']} mb{cand['micro_batch_size']}")
        if md:
            print(f"| {name} | {anchor:.3f} | {layout} | {t:.2f} "
                  f"| {tok_s / 1e3:.0f}k | **{mfu:.3f}** |")
        else:
            print(f"{name:28s} anchor={anchor:.3f} {layout:28s} "
                  f"step={t:.2f}s tok/s={tok_s / 1e3:.0f}k MFU={mfu:.3f}")
    if not md:
        print(f"\nanchor source: {src}")


if __name__ == "__main__":
    main()
