#!/usr/bin/env bash
# Round-5 chip watcher: probe liveness every 20 min; when the pool
# answers, run tools/tpu_round5.py (the prioritized evidence chain) once
# and exit. ONE TPU process at a time throughout.
#
#   bash tools/tpu_watch5.sh [logfile]
set -uo pipefail
cd "$(dirname "$0")/.."

log="${1:-/tmp/tpu_watch5.log}"
echo "[watch5] start $(date -u +%H:%M:%S)" >> "$log"

while true; do
  if timeout 120 python -c "import jax; print(jax.devices())" \
      >> "$log" 2>&1; then
    echo "[watch5] chip ALIVE $(date -u +%H:%M:%S) — evidence chain" \
      >> "$log"
    python tools/tpu_round5.py >> "$log" 2>&1
    echo "[watch5] done $(date -u +%H:%M:%S)" >> "$log"
    exit 0
  fi
  echo "[watch5] wedged $(date -u +%H:%M:%S); sleeping 20m" >> "$log"
  sleep 1200
done
